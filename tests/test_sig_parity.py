"""Golden parity for the signature matcher (grouped hash-equality, the
transfer-optimal TPU path): all three device output forms (match words,
compact row stream, fixed slots) must agree exactly with the CPU reference
trie on the corpora the NFA/dense matchers are held to."""

import os
import random

import numpy as np
import pytest

from maxmq_tpu.matching import TopicIndex
from maxmq_tpu.matching.sig import SigEngine, compile_sig, tokenize_compact
from maxmq_tpu.protocol import Subscription

from test_nfa_parity import normalize, rand_corpus

PATHS = ["word", "compact", "fixed"]


@pytest.fixture(autouse=True)
def _always_device_path(monkeypatch):
    """These tests exist to exercise the DEVICE path; the ADR-008
    small-corpus router must not silently serve them from the trie
    (parity would pass vacuously)."""
    monkeypatch.setattr(SigEngine, "ROUTE_SUBS_MAX", -1)



def run_path(engine, path, topics):
    if path == "word":
        return engine.subscribers_batch(topics)
    if path == "compact":
        return engine.subscribers_compact_batch(topics)
    return engine.subscribers_fixed_batch(topics)


def check_parity(index, topics, paths=PATHS, **engine_kw):
    # both fixed-path device programs: fused Pallas kernel (auto) and the
    # XLA body (False)
    for use_pallas in ("auto", False):
        engine = SigEngine(index, use_pallas=use_pallas, **engine_kw)
        for path in paths:
            got = run_path(engine, path, topics)
            for topic, result in zip(topics, got):
                want = index.subscribers(topic)
                assert normalize(result) == normalize(want), (
                    f"[{path}/pallas={use_pallas}] mismatch on "
                    f"topic {topic!r}")
    return engine


def test_exact_and_wildcard_basics():
    idx = TopicIndex()
    idx.subscribe("c1", Subscription(filter="a/b/c", qos=1))
    idx.subscribe("c2", Subscription(filter="a/+/c", qos=2))
    idx.subscribe("c3", Subscription(filter="a/#"))
    idx.subscribe("c4", Subscription(filter="#"))
    idx.subscribe("c5", Subscription(filter="+"))
    check_parity(idx, ["a/b/c", "a/x/c", "a", "a/b", "x", "x/y",
                       "a/b/c/d", "$SYS/x", "$SYS"])


def test_hash_parent_and_dollar_rules():
    idx = TopicIndex()
    idx.subscribe("c1", Subscription(filter="sport/tennis/#"))
    idx.subscribe("c2", Subscription(filter="$SYS/#"))
    idx.subscribe("c3", Subscription(filter="$SYS/+/x"))
    idx.subscribe("c4", Subscription(filter="+/tennis/+"))
    check_parity(idx, ["sport/tennis", "sport/tennis/p1", "sport",
                       "$SYS/broker/x", "$SYS/broker", "$SYS",
                       "a/tennis/b"])


def test_empty_levels_and_unknown_tokens():
    idx = TopicIndex()
    idx.subscribe("c1", Subscription(filter="/"))
    idx.subscribe("c2", Subscription(filter="//"))
    idx.subscribe("c3", Subscription(filter="+/"))
    idx.subscribe("c4", Subscription(filter="a//b"))
    check_parity(idx, ["/", "//", "a//b", "never-seen-token/x", "a/b",
                       "never/", "/"])


def test_shared_subscriptions_parity():
    idx = TopicIndex()
    idx.subscribe("w1", Subscription(filter="$share/g1/t/+"))
    idx.subscribe("w2", Subscription(filter="$share/g1/t/+"))
    idx.subscribe("w3", Subscription(filter="$share/g2/t/a"))
    idx.subscribe("n1", Subscription(filter="t/a", qos=1))
    check_parity(idx, ["t/a", "t/b", "t", "x"])


def test_overlap_merge_semantics():
    idx = TopicIndex()
    idx.subscribe("c1", Subscription(filter="m/+", qos=0, identifier=3))
    idx.subscribe("c1", Subscription(filter="m/x", qos=2, identifier=9))
    idx.subscribe("c1", Subscription(filter="m/#", qos=1, identifier=4))
    check_parity(idx, ["m/x", "m/y", "m"])


def test_exact_rows_match_on_host():
    # exact-shape filters (full-literal AND '+') never occupy device
    # table width: both are host equality probes; the device carries
    # only the combinatorial '#'-prefix groups
    idx = TopicIndex()
    idx.subscribe("c1", Subscription(filter="a/b/c"))
    idx.subscribe("c2", Subscription(filter="a/b/d"))
    idx.subscribe("c3", Subscription(filter="a/+/c"))
    idx.subscribe("c4", Subscription(filter="a/b/#"))
    engine = check_parity(idx, ["a/b/c", "a/b/d", "a/b", "a/b/c/d"])
    t = engine.tables
    assert sum(len(g.rows) for g in t.host_exact.values()) == 2
    assert sum(len(r) for p in t.host_plus.values()
               for r in p.rows) == 1
    # device rows: only the '#' filter (one group, one padded word)
    assert int(t.group_words.sum()) == 1


def test_too_deep_topic_falls_back():
    idx = TopicIndex()
    idx.subscribe("c1", Subscription(filter="a/#"))
    deep = "a/" + "/".join(str(i) for i in range(80))
    engine = check_parity(idx, [deep], max_levels=8)
    assert engine.fallbacks > 0


def test_mid_depth_filter_matches_via_compact_window():
    # deeper than the word path's max_levels but within the compact
    # DEPTH_CAP: the compact/fixed paths match it on device, the word
    # path falls back (its tokenizer flags the topic as overflow)
    idx = TopicIndex()
    mid_filter = "/".join(str(i) for i in range(20))
    idx.subscribe("c1", Subscription(filter=mid_filter))
    idx.subscribe("c2", Subscription(filter="a/b"))
    check_parity(idx, [mid_filter, "a/b"], max_levels=8)


def test_deep_filter_only_matches_overflow_topics():
    # beyond DEPTH_CAP (63 levels): compiled out of the device tables,
    # matched purely by the CPU fallback that overflow topics already take
    idx = TopicIndex()
    deep_filter = "/".join(str(i) for i in range(70))
    idx.subscribe("c1", Subscription(filter=deep_filter))
    idx.subscribe("c2", Subscription(filter="a/b"))
    engine = check_parity(idx, [deep_filter, "a/b"], max_levels=8)
    assert engine.tables.deep_rows


def test_fixed_slot_overflow_falls_back():
    idx = TopicIndex()
    for i in range(24):
        idx.subscribe(f"c{i}", Subscription(filter=f"x/{i}/+"))
        idx.subscribe(f"d{i}", Subscription(filter=f"+/{i}/y"))
    engine = SigEngine(idx)
    # topic matching >7 rows must still be exact via the CPU fallback
    idx2 = TopicIndex()
    for i in range(12):
        idx2.subscribe(f"c{i}", Subscription(filter=f"x/+/s{i}/#"))
        idx2.subscribe(f"e{i}", Subscription(filter="x/y/+/#"))
    engine2 = SigEngine(idx2)
    got = engine2.subscribers_fixed_batch(["x/y/s0/t"])[0]
    want = idx2.subscribers("x/y/s0/t")
    assert normalize(got) == normalize(want)


def test_incremental_refresh():
    idx = TopicIndex()
    idx.subscribe("c1", Subscription(filter="a/b"))
    engine = SigEngine(idx)
    assert normalize(engine.subscribers("a/b"))[0].keys() == {"c1"}
    idx.subscribe("c2", Subscription(filter="a/+"))
    got = engine.subscribers("a/b")
    assert sorted(got.subscriptions) == ["c1", "c2"]
    idx.unsubscribe("c1", "a/b")
    got = engine.subscribers("a/b")
    assert sorted(got.subscriptions) == ["c2"]


def test_empty_index():
    idx = TopicIndex()
    engine = SigEngine(idx)
    assert len(engine.subscribers("a/b")) == 0
    assert len(engine.subscribers_fixed_batch(["a/b"])[0]) == 0


def test_tokenize_compact_encoding():
    idx = TopicIndex()
    idx.subscribe("c1", Subscription(filter="a/b/c"))
    tables = compile_sig(idx)
    toks, lens, toks32, lengths = tokenize_compact(
        tables, ["a/b", "$SYS/x", "a/" + "/".join(["d"] * 80)])
    assert toks.dtype == np.uint8
    assert lens[0] == 2 and lens[1] == -2          # sign carries '$'
    assert abs(int(lens[2])) == 127                # too deep -> overflow
    assert lengths[0] == 2


@pytest.mark.parametrize("seed", [1, 2, 3, 4])
def test_randomized_parity(seed):
    rng = random.Random(seed)
    idx = TopicIndex()
    filters, topics = rand_corpus(rng, n_filters=120, n_clients=30)
    from maxmq_tpu.matching.topics import valid_filter
    for i, f in enumerate(filters):
        if not valid_filter(f):
            continue
        idx.subscribe(f"c{i % 30}",
                      Subscription(filter=f, qos=rng.randint(0, 2),
                                   identifier=rng.randint(0, 5)))
    check_parity(idx, topics)


def test_filter_matches_topic_rules():
    from maxmq_tpu.matching.topics import filter_matches_topic as fm
    assert fm(("a", "#"), ("a",), False)          # parent rule 4.7.1.2
    assert fm(("a", "#"), ("a", "b", "c"), False)
    assert not fm(("a", "#"), ("b",), False)
    assert fm(("+",), ("x",), False)
    assert not fm(("+",), ("x", "y"), False)
    assert not fm(("#",), ("$SYS",), True)        # [MQTT-4.7.2-1]
    assert not fm(("+", "x"), ("$SYS", "x"), True)
    assert fm(("$SYS", "#"), ("$SYS", "x"), True)
    assert fm(("a", "+", "c"), ("a", "", "c"), False)  # '+' matches empty


def test_pathological_group_count_falls_back_to_trie(monkeypatch):
    # corpora with more wildcard shapes than MAX_GROUPS must keep serving
    # exactly via the CPU trie — never raise on the publish hot path
    import maxmq_tpu.matching.sig as sigmod
    monkeypatch.setattr(sigmod, "MAX_GROUPS", 2)
    idx = TopicIndex()
    # only '#'-prefix shapes occupy device groups now; three distinct
    # ones exceed the patched limit
    idx.subscribe("c1", Subscription(filter="a/+/#"))
    idx.subscribe("c2", Subscription(filter="+/b/#"))
    idx.subscribe("c3", Subscription(filter="a/b/c/#"))
    idx.subscribe("c4", Subscription(filter="x/#"))
    engine = SigEngine(idx)
    for path in PATHS:
        got = run_path(engine, path, ["a/b/c", "x/y"])
        assert normalize(got[0]) == normalize(idx.subscribers("a/b/c"))
        assert normalize(got[1]) == normalize(idx.subscribers("x/y"))
    with pytest.raises(RuntimeError):
        engine.match_fixed(["a/b/c"])
    # corpus shrinks below the limit -> device path resumes
    idx.unsubscribe("c3", "a/b/c/#")
    idx.unsubscribe("c4", "x/#")
    monkeypatch.setattr(sigmod, "MAX_GROUPS", 4096)
    engine.refresh()
    assert engine._state[2] is not None


def test_pallas_multi_chunk_parity(monkeypatch):
    """Exercise the n_chunks > 1 branch of build_fixed_fn (cross-chunk
    candidate merge + short last chunk) by shrinking the chunk width —
    production corpora hit it at ~65K+ device rows."""
    from maxmq_tpu.matching import sig_pallas
    monkeypatch.setattr(sig_pallas, "CHUNK_WORDS", 128)
    rng = random.Random(5)
    idx = TopicIndex()
    segs = [f"s{i}" for i in range(40)]
    for i in range(12_000):
        depth = rng.randint(2, 6)
        levels = [rng.choice(segs) for _ in range(depth)]
        r = rng.random()
        if r < 0.15:
            levels[rng.randrange(depth)] = "+"
        elif r < 0.8:
            # mostly '#' shapes: only those occupy device words now
            if rng.random() < 0.5:
                levels[rng.randrange(depth)] = "+"
            levels = levels[:rng.randint(1, depth)] + ["#"]
        idx.subscribe(f"c{i}", Subscription(filter="/".join(levels),
                                            qos=i % 3))
    tables = compile_sig(idx)
    kplan = sig_pallas.plan(tables)
    assert kplan is not None and kplan["n_chunks"] > 1, kplan
    assert kplan["n_chunks"] * kplan["chunk"] >= kplan["w_pad"]
    topics = ["/".join(rng.choice(segs)
                       for _ in range(rng.randint(1, 7)))
              for _ in range(64)]
    engine = SigEngine(idx, use_pallas=True, fixed_max_rows=14)
    assert engine.pallas_active
    got = engine.subscribers_fixed_batch(topics)
    for topic, result in zip(topics, got):
        assert normalize(result) == normalize(idx.subscribers(topic)), topic


def test_pallas_plan_bounds():
    from maxmq_tpu.matching import sig_pallas
    idx = TopicIndex()
    for i in range(50):
        idx.subscribe(f"c{i}", Subscription(filter=f"a/{i}/+"))
    tables = compile_sig(idx)
    kplan = sig_pallas.plan(tables)
    assert kplan is not None and kplan["tb"] >= 32
    assert kplan["w_pad"] % 128 == 0
    # a 1M-sub-scale table set (tens of thousands of words) must still
    # plan — the batch tile shrinks instead of the kernel declining
    import numpy as np
    big = compile_sig(idx)
    big.group_words = np.asarray([12_000], dtype=np.int32)
    bplan = sig_pallas.plan(big)
    assert bplan is not None and bplan["tb"] >= 8
    # chunking keeps per-call VMEM bounded: even a 3M-word (96M-row)
    # table set plans, with chunk width capped and chunks covering w_pad
    huge = compile_sig(idx)
    huge.group_words = np.asarray([3_000_000], dtype=np.int32)
    hplan = sig_pallas.plan(huge)
    assert hplan is not None
    assert hplan["chunk"] <= sig_pallas.CHUNK_WORDS
    assert hplan["chunk"] * hplan["n_chunks"] >= hplan["w_pad"]


# ------------------------------------------------- staleness overlay

def _frozen_engine(idx, **kw):
    """Engine whose background recompile never runs: matches MUST be
    served exactly via the journal overlay."""
    engine = SigEngine(idx, **kw)
    engine.refresh_soon = lambda: None
    return engine


def test_overlay_serves_mutations_without_recompile():
    idx = TopicIndex()
    idx.subscribe("c1", Subscription(filter="a/+", qos=1))
    idx.subscribe("c2", Subscription(filter="a/b"))
    engine = _frozen_engine(idx)
    base_version = engine.tables.version

    idx.subscribe("c3", Subscription(filter="a/#", qos=2))          # add
    idx.unsubscribe("c2", "a/b")                                    # remove
    idx.subscribe("c1", Subscription(filter="a/+", qos=0))          # replace
    idx.subscribe("s1", Subscription(filter="$share/g/a/+"))        # shared

    for path in PATHS:
        got = run_path(engine, path, ["a/b", "a", "x"])
        for topic, s in zip(["a/b", "a", "x"], got):
            want = idx.subscribers(topic)
            assert normalize(s) == normalize(want), (path, topic)
    # tables never recompiled: served purely by the overlay
    assert engine.tables.version == base_version
    assert engine._overlay is not None and not engine._overlay.empty

    # a real refresh drops the overlay
    engine.refresh()
    assert engine.tables.version == idx.sub_version
    got = engine.subscribers_fixed_batch(["a/b"])[0]
    assert normalize(got) == normalize(idx.subscribers("a/b"))


def test_overlay_journal_gap_resyncs_via_trie(monkeypatch):
    idx = TopicIndex()
    idx.subscribe("c1", Subscription(filter="a/b"))
    engine = _frozen_engine(idx)
    # overflow the journal far past its capacity
    idx._journal = type(idx._journal)(maxlen=4)
    for i in range(50):
        idx.subscribe(f"g{i}", Subscription(filter=f"q/{i}"))
    got = engine.subscribers_fixed_batch(["q/7", "a/b"])
    assert normalize(got[0]) == normalize(idx.subscribers("q/7"))
    assert normalize(got[1]) == normalize(idx.subscribers("a/b"))
    assert engine.fallbacks >= 2


def test_overlay_rebuilds_for_older_tables_after_newer_base():
    """Overlay reuse must key on the construction base, not the
    applied-through version: an overlay rebuilt against newer tables must
    not serve an in-flight batch still holding the old tables (the
    entries between the two versions would be replayed by neither)."""
    from maxmq_tpu.matching.sig import Overlay

    idx = TopicIndex()
    idx.subscribe("c1", Subscription(filter="a/b"))
    engine = _frozen_engine(idx)
    v_old = idx.sub_version

    idx.subscribe("c2", Subscription(filter="a/+"))     # entry in (old, new]
    v_new = idx.sub_version

    # simulate the race: a caller that already swapped to v_new tables
    # rebuilt the shared overlay with base v_new (it replays nothing)
    engine._overlay = Overlay(v_new)

    # an in-flight batch still holding v_old tables asks for its overlay:
    # it must see the (v_old, v_new] subscription
    ov = engine.overlay_for(v_old)
    assert ov is not None and ov != "resync"
    assert ("c2", "a/+") in ov.removed
    assert "c2" in ov.delta.subscribers("a/x").subscriptions


def test_add_row_out_of_range_is_dropped():
    """Padding-word artifacts past the row tables must be dropped, not
    raise IndexError on the publish hot path."""
    from maxmq_tpu.matching.trie import SubscriberSet

    idx = TopicIndex()
    idx.subscribe("c1", Subscription(filter="a/b"))
    engine = SigEngine(idx, auto_refresh=False)
    t = engine.tables
    res = SubscriberSet()
    SigEngine._add_row(res, len(t.row_levels) + 5, t, ["a", "b"], False)
    assert not res.subscriptions and not res.shared


def test_compact_max_rows_validated():
    idx = TopicIndex()
    idx.subscribe("c1", Subscription(filter="a/b"))
    with pytest.raises(ValueError):
        SigEngine(idx, compact_max_rows=255)
    with pytest.raises(ValueError):
        SigEngine(idx, compact_max_rows=0)


def test_decode_rowset_cache_semantics():
    """The C decode pass memoizes results per verified row SET: topics
    with identical matched rows share one SubscriberSet object (the
    broker's own match cache already imposes the treat-as-immutable /
    deep_copy-before-mutating discipline). Parity with the trie must
    hold on both the first (building) and second (cache-hit) pass, and
    deep_copy must isolate."""
    from maxmq_tpu.native import decode_module

    rng = random.Random(9)
    alphabet = [f"t{i}" for i in range(6)]     # tiny: force hot rowsets
    idx = TopicIndex()
    for i in range(400):
        depth = rng.randint(1, 4)
        levels = [rng.choice(alphabet) for _ in range(depth)]
        r = rng.random()
        if r < 0.3:
            levels[rng.randrange(depth)] = "+"
        elif r < 0.5:
            levels = levels[: rng.randint(1, depth)] + ["#"]
        f = "/".join(levels)
        if rng.random() < 0.2:
            f = f"$share/g{rng.randint(0, 2)}/{f}"
        idx.subscribe(f"c{i}", Subscription(filter=f))
    engine = SigEngine(idx, auto_refresh=False)
    topics = ["/".join(rng.choice(alphabet)
                       for _ in range(rng.randint(1, 4)))
              for _ in range(256)]
    topics += topics[:64]                      # literal repeats too

    for _ in range(2):                         # pass 2 = pure cache hits
        got = engine.subscribers_batch(topics)
        for topic, g in zip(topics, got):
            assert normalize(g) == normalize(idx.subscribers(topic)), topic

    if decode_module() is None:
        return                                 # python fallback: no cache
    got = engine.subscribers_batch(topics)
    by_key = {}
    for topic, g in zip(topics, got):
        prev = by_key.setdefault(topic, g)
        assert prev is g or normalize(prev) == normalize(g)
    # repeated topics share the SAME object (cache hit), and deep_copy
    # isolates mutation
    rich = max(got, key=lambda s: len(s.subscriptions))
    if rich.subscriptions:
        cp = rich.deep_copy()
        cid = next(iter(cp.subscriptions))
        del cp.subscriptions[cid]
        assert cid in rich.subscriptions


def test_decode_rate_unit_bench():
    """VERDICT r1 #6: row -> SubscriberSet decode must sustain >= 1M
    rows/s — the per-delivery half that bounds fan-out no matter how
    fast the device matches. The batch path verifies all candidate pairs
    in one numpy pass and only unions verified entries in python."""
    import time

    rng = random.Random(5)
    alphabet = [f"s{i}" for i in range(50)]
    idx = TopicIndex()
    n = 20_000
    for i in range(n):
        depth = rng.randint(2, 6)
        levels = [rng.choice(alphabet) for _ in range(depth)]
        r = rng.random()
        if r < 0.3:
            levels[rng.randrange(depth)] = "+"
        elif r < 0.45:
            levels = levels[: rng.randint(1, depth)] + ["#"]
        idx.subscribe(f"c{i}", Subscription(filter="/".join(levels)))
    engine = SigEngine(idx, auto_refresh=False)
    topics = ["/".join(rng.choice(alphabet)
                       for _ in range(rng.randint(2, 6)))
              for _ in range(4096)]
    ctx = engine.dispatch_fixed(topics)
    got = engine.collect_fixed(topics, ctx)               # warm tables
    rows = sum(len(s.subscriptions) + len(s.shared) for s in got)
    best = 0.0
    for _ in range(5):                      # best-of: capability, not
        t0 = time.perf_counter()            # current machine load
        engine.collect_fixed(topics, ctx)   # fetch + verify + union only
        best = max(best, rows / (time.perf_counter() - t0))
    assert rows > 4096, "corpus produced too few matches to measure"
    if best < 1_000_000 and os.getloadavg()[0] > os.cpu_count() * 0.75:
        pytest.skip(f"box saturated (load {os.getloadavg()[0]:.1f}); "
                    f"measured {best:,.0f} rows/s — capability is "
                    "asserted on an idle box")
    assert best >= 1_000_000, f"decode rate {best:,.0f} rows/s < 1M"


def test_stream_prefetch_shortfall_fetches_rest(monkeypatch):
    """When the EMA hint under-predicts, the unprefetched tail of the
    row stream must be fetched synchronously — force tiny slices so the
    shortfall path actually runs."""
    import maxmq_tpu.matching.sig as sigmod

    idx = TopicIndex()
    for i in range(40):
        idx.subscribe(f"c{i}", Subscription(filter=f"a/{i}/#"))
        idx.subscribe(f"w{i}", Subscription(filter="a/+/x"))
    monkeypatch.setattr(sigmod, "_STREAM_CHUNK", 8)
    engine = SigEngine(idx, auto_refresh=False)
    engine._stream_rows_hint = 0        # prefetch just one tiny slice
    topics = [f"a/{i}/x" for i in range(40)]    # 2 rows per topic
    got = engine.subscribers_fixed_batch(topics)
    for i, (topic, s) in enumerate(zip(topics, got)):
        want = idx.subscribers(topic)
        assert set(s.subscriptions) == set(want.subscriptions), topic
    assert engine._stream_rows_hint > 0     # EMA updated from the batch


def test_retained_churn_never_recompiles():
    from maxmq_tpu.protocol.codec import PacketType as PT
    from maxmq_tpu.protocol.packets import FixedHeader, Packet
    idx = TopicIndex()
    idx.subscribe("c1", Subscription(filter="a/+"))
    engine = SigEngine(idx)
    v = engine.tables.version
    for i in range(5):
        idx.retain(Packet(fixed=FixedHeader(type=PT.PUBLISH),
                          topic=f"a/r{i}", payload=b"x"))
    assert idx.sub_version == v          # retained does not bump
    engine.refresh()
    assert engine.tables.version == v    # and never forces a recompile


def test_fixed_path_bucket_ladder_parity():
    """dispatch_fixed pads the batch axis to a sparse bucket ladder (16,
    powers of 4 to 4096, powers of 2 beyond). Batch sizes straddling the
    ladder edges must decode identically to the trie — pad rows are
    depth-1 '$'-topics that may match nothing (round-3 bucketing)."""
    rng = random.Random(11)
    filters, _ = rand_corpus(rng, 300, 40)
    idx = TopicIndex()
    for i, f in enumerate(filters):
        idx.subscribe(f"cl-{i % 40}", Subscription(filter=f, qos=i % 3))
    engine = SigEngine(idx, auto_refresh=False)
    alphabet = [f"t{i}" for i in range(8)]
    for size in (1, 15, 16, 17, 63, 64, 65, 255, 257):
        topics = ["/".join(rng.choice(alphabet)
                           for _ in range(rng.randint(1, 5)))
                  for _ in range(size)]
        got = engine.subscribers_fixed_batch(topics)
        assert len(got) == size
        for topic, result in zip(topics, got):
            want = idx.subscribers(topic)
            assert normalize(result) == normalize(want), (size, topic)


@pytest.mark.parametrize("seed", [101, 202, 303])
def test_randomized_churn_parity(seed):
    """Subscribe/unsubscribe churn interleaved with fixed-path matches:
    every match must agree with the trie REGARDLESS of where the engine
    is in its overlay/journal/recompile lifecycle (forced rotations and
    overlay-served windows both exercised)."""
    rng = random.Random(seed)
    filters, topics = rand_corpus(rng, 250, 40)
    idx = TopicIndex()
    live: list[tuple[str, str]] = []
    for i, f in enumerate(filters[:120]):
        cid = f"cl-{i % 40}"
        idx.subscribe(cid, Subscription(filter=f, qos=i % 3))
        live.append((cid, f))
    engine = SigEngine(idx, auto_refresh=False)
    pool = filters[120:]
    for step in range(60):
        op = rng.random()
        if op < 0.4 and pool:
            cid = f"cl-{rng.randrange(40)}"
            f = pool.pop(rng.randrange(len(pool)))
            idx.subscribe(cid, Subscription(filter=f,
                                            qos=rng.randrange(3)))
            live.append((cid, f))
        elif op < 0.7 and live:
            cid, f = live.pop(rng.randrange(len(live)))
            idx.unsubscribe(cid, f)
        if rng.random() < 0.25:
            engine.refresh(force=True)      # rotation mid-churn
        batch = [rng.choice(topics) for _ in range(rng.randint(1, 9))]
        got = engine.subscribers_fixed_batch(batch)
        for topic, result in zip(batch, got):
            want = idx.subscribers(topic)
            assert normalize(result) == normalize(want), (seed, step,
                                                          topic)


# --------------------------------------------------------------------
# DeliveryIntents (ADR 007): the fan-out-ready native decode form
# --------------------------------------------------------------------

def _intents_engine(idx, **kw):
    eng = SigEngine(idx, **kw)
    eng.emit_intents = True
    return eng


def _native_mod():
    from maxmq_tpu.native import decode_module
    mod = decode_module()
    if mod is None or not hasattr(mod, "DeliveryIntents"):
        pytest.skip("maxmq_decode extension unavailable")
    return mod


def _saved_chain_params(mod) -> tuple:
    """Chain params in effect, restored verbatim by finally blocks
    (never the hardcoded defaults — ADVICE r5 #3)."""
    from maxmq_tpu.native import chain_params_in_effect
    return chain_params_in_effect(mod)


def _as_set(result):
    to_set = getattr(result, "to_set", None)
    return to_set() if to_set is not None else result


@pytest.mark.parametrize("seed", [11, 12, 13])
def test_intents_parity_randomized(seed):
    """Intents (iterated AND via to_set) match the CPU trie on the same
    randomized corpora the set path is held to."""
    mod = _native_mod()
    rng = random.Random(seed)
    idx = TopicIndex()
    filters, topics = rand_corpus(rng, n_filters=150, n_clients=40)
    from maxmq_tpu.matching.topics import valid_filter
    for i, f in enumerate(filters):
        if not valid_filter(f):
            continue
        idx.subscribe(f"c{i % 40}",
                      Subscription(filter=f, qos=rng.randint(0, 2),
                                   identifier=rng.randint(0, 5)))
    eng = _intents_engine(idx)
    ctx = eng.dispatch_fixed(topics)
    got = eng.collect_fixed(topics, ctx)
    saw_intents = 0
    for topic, result in zip(topics, got):
        want = idx.subscribers(topic)
        if isinstance(result, mod.DeliveryIntents):
            saw_intents += 1
            # iteration surface agrees with the materialized set
            by_iter = {cid: sub for cid, sub in result}
            assert set(by_iter) == set(want.subscriptions), topic
            for cid, sub in by_iter.items():
                w = want.subscriptions[cid]
                assert sub.qos == w.qos, (topic, cid)
                assert dict(sub.identifiers) == dict(w.identifiers), \
                    (topic, cid)
                assert result.has_client(cid)
            assert not result.has_client("no-such-client")
            assert len(result) == len(want.subscriptions) + sum(
                len(m) for m in want.shared.values())
        assert normalize(_as_set(result)) == normalize(want), topic
    assert saw_intents, "native intents path never engaged"


def test_intents_rowset_cache_identity():
    """Repeated topics resolve to the SAME cached intents object (the
    whole point: zero construction on the hot repeat path)."""
    _native_mod()
    idx = TopicIndex()
    for i in range(50):
        idx.subscribe(f"c{i}", Subscription(filter="hot/#", qos=1))
    eng = _intents_engine(idx)
    t = ["hot/x"] * 8 + ["hot/y"] * 8
    got = eng.collect_fixed(t, eng.dispatch_fixed(t))
    assert got[0] is got[7], "same topic should alias one cached object"
    assert got[0] is got[8], "same ROW SET should alias too"
    # to_set is cached on the object
    assert got[0].to_set() is got[0].to_set()


def test_intents_empty_and_shared_surface():
    _native_mod()
    idx = TopicIndex()
    idx.subscribe("s1", Subscription(filter="$share/g/sh/+", qos=1))
    idx.subscribe("p1", Subscription(filter="sh/+", qos=2))
    eng = _intents_engine(idx)
    t = ["sh/a", "nomatch/zz"]
    got = eng.collect_fixed(t, eng.dispatch_fixed(t))
    r, empty = got
    assert ("g", "$share/g/sh/+") in r.shared
    assert r.has_client("p1") and not r.has_client("s1")
    assert len(empty) == 0 and list(empty) == []
    assert empty.shared == {}


def test_intents_overlay_window_degrades_to_sets():
    """During a journal overlay window results must carry the mutation
    (merge_delta needs set semantics); parity must hold throughout."""
    _native_mod()
    idx = TopicIndex()
    for i in range(40):
        idx.subscribe(f"c{i}", Subscription(filter=f"ov/{i}/#", qos=1))
    eng = _frozen_engine(idx)          # no auto recompile
    eng.emit_intents = True
    idx.subscribe("late", Subscription(filter="ov/1/#", qos=2))
    t = ["ov/1/x"]
    got = eng.collect_fixed(t, eng.dispatch_fixed(t))
    want = idx.subscribers("ov/1/x")
    assert normalize(_as_set(got[0])) == normalize(want)
    assert "late" in _as_set(got[0]).subscriptions


def test_intents_chained_base_parity():
    """Fat-row topics build CHAINED intents (immutable single-row base +
    per-topic tail with slot overrides) — the cold-stream wall killer.
    Every consumer surface must agree with the trie: iteration (dedup,
    merged qos/identifiers), n, len, has_client, to_set, $share maps."""
    _native_mod()
    idx = TopicIndex()
    # fat '#' bucket well past g_chain_min_base (default 64,
    # native/maxmq_decode.cpp)
    for i in range(150):
        idx.subscribe(f"fat{i}", Subscription(filter="iot/dev/#", qos=1))
    # thin rows; fat3/fat5 overlap the fat row -> overrides (merged
    # qos max + v5 identifier union); solo* are pure tail entries
    idx.subscribe("fat3", Subscription(filter="iot/dev/a/b", qos=2,
                                       identifier=7))
    idx.subscribe("fat5", Subscription(filter="iot/dev/+/b", qos=0))
    idx.subscribe("solo1", Subscription(filter="iot/dev/a/b", qos=2))
    idx.subscribe("solo2", Subscription(filter="iot/dev/+/b", qos=1,
                                        identifier=3))
    idx.subscribe("sh1", Subscription(filter="$share/g/iot/dev/#", qos=1))
    idx.subscribe("sh2", Subscription(filter="$share/g/iot/dev/a/b",
                                      qos=1))
    eng = _intents_engine(idx)
    eng.route_small = False
    topics = ["iot/dev/a/b",   # chain: 2 tail entries + 2 overrides
              "iot/dev/x/b",   # chain: 1 tail + 1 override
              "iot/dev/z",     # single fat row: plain (not chained)
              "nope/x"]        # empty
    got = eng.collect_fixed(topics, eng.dispatch_fixed(topics))
    assert got[0].chained and got[1].chained
    assert not got[2].chained and not got[3].chained
    for topic, r in zip(topics, got):
        want = idx.subscribers(topic)
        by_iter = {}
        for cid, sub in r:
            assert cid not in by_iter, f"dup {cid} on {topic}"
            by_iter[cid] = sub
        assert len(by_iter) == r.n, topic
        assert set(by_iter) == set(want.subscriptions), topic
        for cid, sub in by_iter.items():
            w = want.subscriptions[cid]
            assert sub.qos == w.qos, (topic, cid)
            assert dict(sub.identifiers) == dict(w.identifiers), \
                (topic, cid)
            assert r.has_client(cid)
        assert not r.has_client("no-such-client")
        assert len(r) == len(want.subscriptions) + sum(
            len(m) for m in want.shared.values()), topic
        assert normalize(r.to_set()) == normalize(want), topic
    # chains are cached per row set and alias across topics
    again = eng.collect_fixed(topics, eng.dispatch_fixed(topics))
    assert again[0] is got[0] and again[1] is got[1]


def test_intents_chained_randomized_fat_corpus():
    """Randomized corpora with fat '#' buckets: chained vs trie parity
    over many distinct row sets (cold-stream shape)."""
    _native_mod()
    rng = random.Random(99)
    idx = TopicIndex()
    for i in range(200):
        idx.subscribe(f"f{i}", Subscription(filter="b/#",
                                            qos=rng.randint(0, 2)))
    # thin overlapping filters, some reusing fat clients
    for i in range(60):
        cid = f"f{rng.randrange(200)}" if i % 3 else f"solo{i}"
        seg = rng.choice(["b/x", "b/+", f"b/{i}", f"b/x/{i}", "b/+/+"])
        idx.subscribe(cid, Subscription(filter=seg,
                                        qos=rng.randint(0, 2),
                                        identifier=rng.randint(0, 4)))
    eng = _intents_engine(idx)
    eng.route_small = False
    topics = [rng.choice(["b/x", "b/q", f"b/{i}", f"b/x/{i}",
                          f"b/{i}/z"]) for i in range(120)]
    got = eng.collect_fixed(topics, eng.dispatch_fixed(topics))
    saw_chain = 0
    for topic, r in zip(topics, got):
        want = idx.subscribers(topic)
        saw_chain += bool(getattr(r, "chained", False))
        by_iter = {}
        for cid, sub in r:
            assert cid not in by_iter, (topic, cid)
            by_iter[cid] = sub
        assert len(by_iter) == r.n, topic
        assert normalize(r.to_set()) == normalize(want), topic
        for cid, sub in by_iter.items():
            w = want.subscriptions[cid]
            assert (sub.qos, dict(sub.identifiers)) == \
                (w.qos, dict(w.identifiers)), (topic, cid)
    assert saw_chain, "chained path never engaged"


def test_intents_chained_equals_full_union_flags():
    """A chained union must be INDISTINGUISHABLE from the full union of
    the same row sets — including the flag fields normalize() ignores
    (merge_subscription takes no_local/RAP/RH from the newer filter, so
    a naive chain would reverse the donor when the fat row anchors
    first). Full-field A/B via the test-only _set_chain_enabled."""
    mod = _native_mod()
    if not hasattr(mod, "_set_chain_enabled"):
        pytest.skip("chain toggle unavailable")

    def build_engine():
        idx = TopicIndex()
        for i in range(150):
            idx.subscribe(f"fat{i}", Subscription(
                filter="fl/dev/#", qos=1, retain_handling=0))
        # overlapping clients with DISTINCT flag values per filter
        idx.subscribe("fat3", Subscription(
            filter="fl/dev/a/b", qos=2, retain_handling=2,
            no_local=True, identifier=7))
        idx.subscribe("fat5", Subscription(
            filter="fl/dev/+/b", qos=0, retain_as_published=True,
            retain_handling=1))
        idx.subscribe("fat7", Subscription(
            filter="fl/+/a/b", qos=1, retain_handling=2, identifier=2))
        eng = _intents_engine(idx)
        eng.route_small = False
        return eng

    topics = ["fl/dev/a/b", "fl/dev/x/b", "fl/dev/z/q"]

    def snapshot(eng):
        got = eng.collect_fixed(topics, eng.dispatch_fixed(topics))
        out = []
        for r in got:
            out.append(sorted(
                (cid, s.filter, s.qos, s.no_local,
                 s.retain_as_published, s.retain_handling,
                 s.identifier, tuple(sorted(s.identifiers.items())))
                for cid, s in r))
        return got, out

    try:
        chained_res, chained = snapshot(build_engine())
        assert any(getattr(r, "chained", False) for r in chained_res)
        mod._set_chain_enabled(False)
        plain_res, plain = snapshot(build_engine())
        assert not any(getattr(r, "chained", False) for r in plain_res)
    finally:
        mod._set_chain_enabled(True)
    assert chained == plain


@pytest.mark.parametrize("seed", [41, 42, 43, 44])
def test_intents_chain_fuzz_equivalence(seed):
    """Randomized full-field equivalence: for corpora with several fat
    buckets, overlapping thin filters, v5 identifiers and $share, the
    chained build must equal the full union on EVERY field of every
    delivered record, for every topic (not just the normalize
    projection)."""
    mod = _native_mod()
    if not hasattr(mod, "_set_chain_params"):
        pytest.skip("chain toggle unavailable")
    rng = random.Random(seed)

    def build_engine():
        idx = TopicIndex()
        for b in range(rng.randint(1, 3)):
            root = rng.choice(["fz", "fz/x", "deep/fz"])
            for i in range(rng.randint(70, 140)):
                idx.subscribe(f"b{b}c{i}", Subscription(
                    filter=f"{root}/#", qos=rng.randint(0, 2),
                    retain_handling=rng.randint(0, 2)))
        for i in range(rng.randint(10, 40)):
            cid = (f"b0c{rng.randrange(70)}" if i % 2 else f"s{i}")
            f = rng.choice(["fz/+", "fz/x/+", "fz/x/a", f"fz/t{i}",
                            "deep/fz/+/q", "$share/g/fz/#",
                            "fz/x/a/b"])
            idx.subscribe(cid, Subscription(
                filter=f, qos=rng.randint(0, 2),
                no_local=bool(rng.getrandbits(1)),
                retain_as_published=bool(rng.getrandbits(1)),
                identifier=rng.randint(0, 6)))
        eng = _intents_engine(idx)
        eng.route_small = False
        return eng

    topics = [rng.choice(["fz/x/a", "fz/x/a/b", "fz/q", "fz/x/zz",
                          f"fz/t{rng.randrange(40)}", "deep/fz/m/q",
                          "fz/x/a/b/c", "none/x"]) for _ in range(60)]

    def snapshot(eng):
        got = eng.collect_fixed(topics, eng.dispatch_fixed(topics))
        out = []
        for r in got:
            s = r.to_set() if hasattr(r, "to_set") else r
            out.append((sorted(
                (cid, v.filter, v.qos, v.no_local,
                 v.retain_as_published, v.retain_handling, v.identifier,
                 tuple(sorted(v.identifiers.items())))
                for cid, v in s.subscriptions.items()),
                sorted((g, f, tuple(sorted(m)))
                       for (g, f), m in s.shared.items())))
        return got, out

    state = rng.getstate()
    saved = _saved_chain_params(mod)
    try:
        mod._set_chain_params(32, 1, 1)    # chain aggressively
        chained_res, chained = snapshot(build_engine())
        assert any(getattr(r, "chained", False) for r in chained_res)
        mod._set_chain_enabled(False)
        rng.setstate(state)                # identical corpus
        _, plain = snapshot(build_engine())
    finally:
        mod._set_chain_enabled(True)
        mod._set_chain_params(*saved)
    assert chained == plain


def test_table_release_breaks_cycle_on_rotation():
    """Dropping a compiled snapshot must release its cached intents:
    the capsule<->icache cycle is not GC-collectible (VERDICT: leak
    would grow per subscription rotation)."""
    import gc
    import weakref
    mod = _native_mod()
    idx = TopicIndex()
    for i in range(30):
        idx.subscribe(f"c{i}", Subscription(filter=f"rl/{i}", qos=0))
    eng = _intents_engine(idx)
    t = [f"rl/{i}" for i in range(30)]
    got = eng.collect_fixed(t, eng.dispatch_fixed(t))
    tables = eng.tables
    tref = weakref.ref(tables)
    del got, tables
    # rotation: force a recompile; the old snapshot is dropped
    idx.subscribe("newcl", Subscription(filter="rl/0", qos=1))
    eng.refresh(force=True)
    for _ in range(3):
        gc.collect()
    assert tref() is None, "old snapshot still alive after rotation"


# ---------------------------------------------------------------------------
# Device-free host match (subscribers_host_batch): the batcher's
# low-occupancy bypass path — exact/'+'/'#' signature probes + the same
# C decode, no device dispatch at all.


@pytest.mark.parametrize("seed", [31, 32, 33])
def test_host_batch_parity_randomized(seed):
    """The host-only path (host_hash_rows completing the probe set)
    matches the trie exactly, in both result forms."""
    rng = random.Random(seed)
    idx = TopicIndex()
    filters, topics = rand_corpus(rng, n_filters=150, n_clients=40)
    from maxmq_tpu.matching.topics import valid_filter
    for i, f in enumerate(filters):
        if not valid_filter(f):
            continue
        idx.subscribe(f"c{i % 40}",
                      Subscription(filter=f, qos=rng.randint(0, 2),
                                   identifier=rng.randint(0, 5)))
    for emit in (False, True):
        eng = SigEngine(idx)
        eng.emit_intents = emit
        got = eng.subscribers_host_batch(topics)
        for topic, result in zip(topics, got):
            want = idx.subscribers(topic)
            assert normalize(_as_set(result)) == normalize(want), \
                (topic, emit)
        assert eng.host_matches == len(topics)


def test_host_batch_never_touches_device(monkeypatch):
    """The host path must stay correct with the device program broken —
    that independence is exactly what the bypass relies on when the
    link is degraded."""
    idx = TopicIndex()
    idx.subscribe("c1", Subscription(filter="h/b/c", qos=1))
    idx.subscribe("c2", Subscription(filter="h/+/c", qos=2))
    idx.subscribe("c3", Subscription(filter="h/#"))
    idx.subscribe("c4", Subscription(filter="#"))
    idx.subscribe("c5", Subscription(filter="$share/g/h/#"))
    eng = SigEngine(idx)
    eng.refresh(force=True)

    def boom(*a, **k):
        raise AssertionError("device program invoked on the host path")

    monkeypatch.setattr(eng, "dispatch_fixed", boom)
    state = list(eng._state)
    state[6] = boom                      # the jitted fixed program
    eng._state = tuple(state)
    topics = ["h/b/c", "h/x/c", "h", "h/deep/er/still", "x", "$SYS/x"]
    got = eng.subscribers_host_batch(topics)
    for topic, result in zip(topics, got):
        assert normalize(_as_set(result)) == \
            normalize(idx.subscribers(topic)), topic


def test_single_topic_surface_serves_from_host():
    """engine.subscribers() never touches the device: trie below the
    measured corpus crossover, the device-free host path above it."""
    idx = TopicIndex()
    idx.subscribe("c1", Subscription(filter="s/+/t", qos=1))
    eng = SigEngine(idx)
    eng.route_small = False
    # small corpus: trie (its walk undercuts the host call's fixed cost)
    got = eng.subscribers(topic="s/x/t")
    assert "c1" in _as_set(got).subscriptions
    assert eng.host_matches == 0
    # past the crossover: the host path
    eng.HOST_SINGLE_SUBS_MIN = 0
    got = eng.subscribers(topic="s/x/t")
    assert "c1" in _as_set(got).subscriptions
    assert eng.host_matches == 1


def test_intents_multi_base_composition():
    """Round-5 multi-base chains: a row set holding several DISJOINT
    fat rows composes per-row cached bases (fat-row combinations never
    repeat on cold streams, but each row does — measured in
    BASELINE-COMPARE) and must stay full-field-identical to both the
    legacy single-fattest-base form and the full union. A client
    subscribed into TWO fat rows makes both rows impure: at most one
    may anchor, and parity must still hold."""
    mod = _native_mod()
    if not hasattr(mod, "_set_multi_base"):
        pytest.skip("multi-base toggle unavailable")

    def build_engine():
        idx = TopicIndex()
        # three fat buckets all matching mb/x/a/b
        for i in range(90):
            idx.subscribe(f"fa{i}", Subscription(filter="mb/#", qos=1))
        for i in range(40):
            idx.subscribe(f"fb{i}", Subscription(
                filter="mb/x/#", qos=0, retain_handling=1))
        for i in range(24):
            idx.subscribe(f"fc{i}", Subscription(filter="mb/x/a/#",
                                                 qos=2))
        # impure pair: one client delivering from TWO fat rows
        idx.subscribe("fa0", Subscription(filter="mb/x/#", qos=2,
                                          no_local=True))
        # thin tail incl. a base-collision override with v5 identifier
        idx.subscribe("thin1", Subscription(filter="mb/x/a/b", qos=1))
        idx.subscribe("fb3", Subscription(filter="mb/+/a/b", qos=2,
                                          identifier=5))
        eng = _intents_engine(idx)
        eng.route_small = False
        return eng

    topics = ["mb/x/a/b", "mb/x/a/c", "mb/q", "mb/x/zz"]

    def snapshot(eng):
        got = eng.collect_fixed(topics, eng.dispatch_fixed(topics))
        out = []
        for r in got:
            s = r.to_set() if hasattr(r, "to_set") else r
            out.append((sorted(
                (cid, v.filter, v.qos, v.no_local,
                 v.retain_as_published, v.retain_handling, v.identifier,
                 tuple(sorted(v.identifiers.items())))
                for cid, v in s.subscriptions.items()),
                sorted((g, f, tuple(sorted(m)))
                       for (g, f), m in s.shared.items())))
        return got, out

    def max_bases(results):
        best = 0
        for r in results:
            rep = repr(r)
            if "bases=" in rep:
                best = max(best, int(rep.split("bases=")[1].split(",")[0]))
        return best

    saved = _saved_chain_params(mod)
    try:
        mod._set_chain_params(32, 4, 1)
        multi_res, multi = snapshot(build_engine())
        assert max_bases(multi_res) >= 2, \
            [repr(r) for r in multi_res]
        mod._set_multi_base(False)
        single_res, single = snapshot(build_engine())
        assert max_bases(single_res) <= 1
        mod._set_chain_enabled(False)
        _, plain = snapshot(build_engine())
    finally:
        mod._set_chain_enabled(True)
        mod._set_multi_base(True)
        mod._set_chain_params(*saved)
    assert multi == plain
    assert single == plain


# --------------------------------------------------------------------
# Dual-width bit-planes (ADR 010): packed 16-bit plane compare for
# groups whose signatures admit an injective 16-bit fold, 32-bit planes
# for the rest — exact parity required in every mix.
# --------------------------------------------------------------------


def _engineered_width_corpus(monkeypatch, max_rows16=8):
    """Corpus with BOTH plane widths: one '#'-shape with more unique
    rows than the (patched) eligibility bound stays 32-bit, a smaller
    shape goes 16-bit."""
    import maxmq_tpu.matching.sig as sigmod
    monkeypatch.setattr(sigmod, "W16_MAX_GROUP_ROWS", max_rows16)
    idx = TopicIndex()
    for i in range(30):                        # shape (#, depth 1): 30 rows
        idx.subscribe(f"w{i}", Subscription(filter=f"r{i}/#", qos=1))
    for i in range(5):                         # shape (#, depth 2): 5 rows
        idx.subscribe(f"n{i}", Subscription(filter=f"x/y{i}/#", qos=2))
    idx.subscribe("sh", Subscription(filter="$share/g/x/y0/#"))
    idx.subscribe("pl", Subscription(filter="x/+/q"))     # host-probed
    idx.subscribe("ex", Subscription(filter="r0/exact"))  # host-probed
    return idx


def test_mixed_width_compile_layout(monkeypatch):
    """Eligibility splits per group; 16-bit groups are laid out LAST
    (contiguous word regions per width); folds are injective and avoid
    the 0xFFFF pad poison."""
    idx = _engineered_width_corpus(monkeypatch)
    tables = compile_sig(idx)
    w16 = tables.group_w16
    assert w16.any() and (~w16).any(), "need both widths"
    # 32-bit groups strictly precede 16-bit groups
    first16 = int(np.argmax(w16))
    assert w16[first16:].all() and not w16[:first16].any()
    from maxmq_tpu.matching.sig import _fold16
    for gi, g in enumerate(tables.groups):
        rows = np.asarray(g.rows)
        sig16 = tables.row_sig16[rows]
        if w16[gi]:
            assert tables.fold_mult[gi] % 2 == 1
            assert (sig16 != 0xFFFF).all()
            assert len(np.unique(sig16)) == len(sig16), "fold not injective"
            # the stored fold IS the multiply-shift of the row sigs
            np.testing.assert_array_equal(
                sig16, _fold16(tables.row_sig[rows], tables.fold_mult[gi]))
        else:
            assert tables.fold_mult[gi] == 0
    # pad rows carry the 16-bit poison
    pad = np.ones(len(tables.row_sig16), dtype=bool)
    for g in tables.groups:
        pad[np.asarray(g.rows)] = False
    assert (tables.row_sig16[pad] == 0xFFFF).all()


def test_mixed_width_parity_and_equality(monkeypatch):
    """The mixed-width kernel must be bit-exact with the 32-bit-forced
    kernel AND the CPU trie at the decoded-result boundary, on a corpus
    where some groups are 16-bit-eligible and some are not (16-bit fold
    collisions only add host-verified candidates or overflow to the
    exact trie fallback — results never change)."""
    idx = _engineered_width_corpus(monkeypatch)
    rng = random.Random(4)
    topics = ([f"r{i}/t/{j}" for i in range(30) for j in (0, 1)]
              + [f"x/y{i}/deep/er" for i in range(5)]
              + ["x/zz/q", "r0/exact", "$SYS/x", "x/y0", "none/here"]
              + ["/".join(rng.choice(["r0", "x", "y0", "q", "zz"])
                          for _ in range(rng.randint(1, 5)))
                 for _ in range(40)])
    results = {}
    for kw in ("auto", "32"):
        for use_pallas in ("auto", False):
            engine = SigEngine(idx, use_pallas=use_pallas,
                               kernel_width=kw)
            got = engine.subscribers_fixed_batch(topics)
            for topic, result in zip(topics, got):
                want = idx.subscribers(topic)
                assert normalize(result) == normalize(want), (
                    f"[width={kw}/pallas={use_pallas}] {topic!r}")
            if use_pallas == "auto":
                assert engine.pallas_active
                plan = engine.kernel_plan
                assert plan is not None
                if kw == "auto":
                    assert plan["groups16"] and plan["groups32"]
                else:
                    assert plan["groups16"] == 0
                results[kw] = [normalize(r) for r in got]
    assert results["auto"] == results["32"]


def test_mixed_width_all_paths_parity(monkeypatch):
    """word/compact/fixed paths stay exact on a dual-width table set
    (word + compact run the unchanged 32-bit XLA body over the
    REORDERED row layout — the reorder itself must be seamless)."""
    idx = _engineered_width_corpus(monkeypatch)
    check_parity(idx, [f"r{i}/a" for i in range(8)]
                 + ["x/y0/b/c", "x/y3", "x/q/q", "$share/x", "r5"])


def test_plan_force_width32(monkeypatch):
    """force_width32 plans the SAME tables all-32: word totals are
    conserved and the predicted plane passes drop in the mixed plan."""
    from maxmq_tpu.matching import sig_pallas

    idx = _engineered_width_corpus(monkeypatch)
    tables = compile_sig(idx)
    mixed = sig_pallas.plan(tables)
    forced = sig_pallas.plan(tables, force_width32=True)
    assert mixed is not None and forced is not None
    assert mixed["n_words16"] > 0 and forced["n_words16"] == 0
    assert (mixed["n_words32"] + mixed["n_words16"]
            == forced["n_words32"])
    assert forced["groups16"] == 0
    # per padded column the packed compare halves the pass count
    assert (mixed["plane_passes_per_topic"]
            < 32 * (mixed["n_chunks32"] + mixed["n_chunks16"])
            * mixed["chunk"])


def test_kernel_width_arg_validated():
    idx = TopicIndex()
    idx.subscribe("c1", Subscription(filter="a/b"))
    with pytest.raises(ValueError):
        SigEngine(idx, kernel_width="16")


def test_randomized_mixed_width_churn_parity(monkeypatch):
    """Randomized corpora + churn under a small eligibility bound so
    recompiles keep flipping groups between widths — every match must
    stay exact through rotations."""
    import maxmq_tpu.matching.sig as sigmod
    monkeypatch.setattr(sigmod, "W16_MAX_GROUP_ROWS", 6)
    rng = random.Random(77)
    filters, topics = rand_corpus(rng, 200, 30)
    idx = TopicIndex()
    from maxmq_tpu.matching.topics import valid_filter
    live = []
    for i, f in enumerate(filters[:120]):
        if not valid_filter(f):
            continue
        cid = f"cl-{i % 30}"
        idx.subscribe(cid, Subscription(filter=f, qos=i % 3))
        live.append((cid, f))
    engine = SigEngine(idx, auto_refresh=False)
    pool = [f for f in filters[120:] if valid_filter(f)]
    for step in range(30):
        if rng.random() < 0.5 and pool:
            cid = f"cl-{rng.randrange(30)}"
            f = pool.pop()
            idx.subscribe(cid, Subscription(filter=f, qos=1))
            live.append((cid, f))
        elif live:
            cid, f = live.pop(rng.randrange(len(live)))
            idx.unsubscribe(cid, f)
        if rng.random() < 0.3:
            engine.refresh(force=True)
        batch = [rng.choice(topics) for _ in range(5)]
        got = engine.subscribers_fixed_batch(batch)
        for topic, result in zip(batch, got):
            want = idx.subscribers(topic)
            assert normalize(result) == normalize(want), (step, topic)
