"""MockListener: the in-memory listener fake (reference parity:
vendor/.../v2/listeners/mock.go — engine tests there run without
sockets; ours must too). Drives a real broker session over the paired
in-memory streams with hand-assembled wire bytes."""

import asyncio

from test_golden_transcripts import (CONNACK_V4, CONNECT_V4, SUBACK_V4,
                                     SUBSCRIBE_V4, expect)

from maxmq_tpu.broker import Broker, BrokerOptions, Capabilities
from maxmq_tpu.broker.listeners import MockListener
from maxmq_tpu.hooks import AllowHook


async def test_mock_listener_full_session():
    b = Broker(BrokerOptions(capabilities=Capabilities(
        sys_topic_interval=0, receive_maximum=0, topic_alias_maximum=0,
        maximum_packet_size=0)))
    b.add_hook(AllowHook())
    lst = b.add_listener(MockListener("mock1", "mem://"))
    await b.serve()
    try:
        assert lst.protocol == "mock"
        assert lst.serving.is_set()
        reader, writer = await lst.connect()
        writer.write(CONNECT_V4)
        await expect(reader, CONNACK_V4, "connack over mock")
        writer.write(SUBSCRIBE_V4)
        await expect(reader, SUBACK_V4, "suback over mock")
        # PUBLISH "g/t" qos0 "hi" [MQTT-3.3] -> echoed to the subscriber
        pub = bytes.fromhex("3007" + "0003" + "672f74" + "6869")
        writer.write(pub)
        await expect(reader, pub, "qos0 echo over mock")
        # writer close semantics: feeds EOF to the broker side
        assert not writer.is_closing()
        writer.close()
        assert writer.is_closing()
        await writer.wait_closed()
        await asyncio.sleep(0.05)
        await lst.close()
        assert not lst.serving.is_set()
    finally:
        await b.close()


async def test_mock_writer_surface():
    """_QueueWriter duck-types the StreamWriter bits the broker uses."""
    lst = MockListener("m2", "mem://")

    async def establish(lid, reader, writer):
        data = await reader.readexactly(3)
        writer.write(b"ok:" + data)
        await writer.drain()
        assert writer.get_extra_info("peername", "none") == "none"
        writer.close()

    await lst.serve(establish)
    reader, writer = await lst.connect()
    writer.write(b"abc")
    assert await asyncio.wait_for(reader.readexactly(6), 5) == b"ok:abc"
    assert await reader.read() == b""      # EOF after server close
    writer.close()
    await writer.wait_closed()
