"""Test harness config: force the CPU backend with 8 virtual devices so the
mesh/sharding tests run without real TPU hardware (the driver separately
dry-runs the multi-chip path)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
