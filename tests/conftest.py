"""Test harness config: force the CPU backend with 8 virtual devices so the
mesh/sharding tests run without real TPU hardware (the driver separately
dry-runs the multi-chip path)."""

import asyncio
import inspect
import os

import pytest

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# The image's sitecustomize pins jax_platforms to the hardware backend,
# overriding the env var — pin it back to cpu before any backend init.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests under asyncio.run (no pytest-asyncio in the
    image)."""
    func = pyfuncitem.obj
    if inspect.iscoroutinefunction(func):
        kwargs = {name: pyfuncitem.funcargs[name]
                  for name in pyfuncitem._fixtureinfo.argnames}
        # tests that boot compile-heavy stages (mesh XLA programs) opt
        # into a longer deadline via `_async_timeout` on the function
        deadline = getattr(func, "_async_timeout", 30)
        asyncio.run(asyncio.wait_for(func(**kwargs), timeout=deadline))
        return True
    return None
