"""Test harness config: force the CPU backend with 8 virtual devices so the
mesh/sharding tests run without real TPU hardware (the driver separately
dry-runs the multi-chip path). Also enforces the per-test timeout cap
(pyproject ``timeout``) when pytest-timeout isn't installed — one hung
device call must fail ONE test with a traceback, not consume the whole
tier-1 budget."""

import asyncio
import importlib.util
import inspect
import os

import pytest

_HAVE_PYTEST_TIMEOUT = importlib.util.find_spec("pytest_timeout") is not None

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# The image's sitecustomize pins jax_platforms to the hardware backend,
# overriding the env var — pin it back to cpu before any backend init.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


if not _HAVE_PYTEST_TIMEOUT:
    # Fallback mini-plugin mirroring pytest-timeout's config surface
    # (ini ``timeout`` / ``@pytest.mark.timeout(N)``, signal method):
    # CI installs the real plugin; this image doesn't ship it, and the
    # 870s tier-1 budget cannot absorb a single wedged device call.
    def pytest_addoption(parser):
        parser.addini("timeout", "per-test timeout in seconds "
                      "(conftest fallback for pytest-timeout)",
                      default="0")
        parser.addini("timeout_method", "accepted for pytest-timeout "
                      "compatibility; the fallback always uses signal",
                      default="signal")

    def _item_timeout(item) -> float:
        marker = item.get_closest_marker("timeout")
        if marker is not None and marker.args:
            return float(marker.args[0])
        try:
            return float(item.config.getini("timeout") or 0)
        except ValueError:
            return 0.0

    @pytest.hookimpl(hookwrapper=True)
    def pytest_runtest_call(item):
        import faulthandler
        import signal
        import threading

        timeout = _item_timeout(item)
        if (timeout <= 0 or not hasattr(signal, "SIGALRM")
                or threading.current_thread()
                is not threading.main_thread()):
            yield
            return

        def on_alarm(signum, frame):
            # all-thread dump FIRST: the hang is usually in a worker
            # thread (device dispatch), and the failing frame alone
            # wouldn't say which call wedged
            faulthandler.dump_traceback()
            pytest.fail(f"test timed out after {timeout:.0f}s "
                        "(conftest timeout fallback)", pytrace=False)

        old = signal.signal(signal.SIGALRM, on_alarm)
        signal.setitimer(signal.ITIMER_REAL, timeout)
        try:
            yield
        finally:
            signal.setitimer(signal.ITIMER_REAL, 0)
            signal.signal(signal.SIGALRM, old)


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests under asyncio.run (no pytest-asyncio in the
    image)."""
    func = pyfuncitem.obj
    if inspect.iscoroutinefunction(func):
        kwargs = {name: pyfuncitem.funcargs[name]
                  for name in pyfuncitem._fixtureinfo.argnames}
        # tests that boot compile-heavy stages (mesh XLA programs) opt
        # into a longer deadline via `_async_timeout` on the function
        deadline = getattr(func, "_async_timeout", 30)
        asyncio.run(asyncio.wait_for(func(**kwargs), timeout=deadline))
        return True
    return None
