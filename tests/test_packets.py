"""Packet codec conformance: golden wire vectors (hand-computed from the MQTT
specs, in the spirit of the reference's tpackets corpus), roundtrips for every
packet type at v3/v4/v5, and malformed-input rejection."""

import pytest

from maxmq_tpu.protocol import (
    FixedHeader,
    MalformedPacketError,
    Packet,
    PacketType as PT,
    Properties,
    ProtocolError,
    Subscription,
    Will,
    codes,
    parse_stream,
)


def roundtrip(p: Packet) -> Packet:
    wire = p.encode()
    buf = bytearray(wire)
    frames = list(parse_stream(buf))
    assert len(frames) == 1 and not buf
    fh, body = frames[0]
    return Packet.decode(fh, body, protocol_version=p.protocol_version)


# ---------------------------------------------------------------------------
# Golden vectors
# ---------------------------------------------------------------------------

def test_connect_v4_golden():
    p = Packet(fixed=FixedHeader(type=PT.CONNECT), protocol_version=4,
               clean_start=True, keepalive=60, client_id="abc")
    assert p.encode() == bytes.fromhex("100f00044d5154540402003c0003616263")


def test_connect_v311_decode_golden():
    wire = bytes.fromhex("100f00044d5154540402003c0003616263")
    buf = bytearray(wire)
    fh, body = next(parse_stream(buf))
    p = Packet.decode(fh, body)
    assert p.protocol_name == "MQTT"
    assert p.protocol_version == 4
    assert p.clean_start is True
    assert p.keepalive == 60
    assert p.client_id == "abc"
    assert p.will is None


def test_publish_qos1_v4_golden():
    p = Packet(fixed=FixedHeader(type=PT.PUBLISH, qos=1), topic="a/b",
               packet_id=10, payload=b"hi")
    # 32 09 0003 'a/b' 000a 'hi'
    assert p.encode() == b"\x32\x09\x00\x03a/b\x00\x0ahi"


def test_subscribe_v4_golden():
    p = Packet(fixed=FixedHeader(type=PT.SUBSCRIBE), packet_id=1,
               filters=[Subscription(filter="s/#", qos=1)])
    assert p.encode() == b"\x82\x08\x00\x01\x00\x03s/#\x01"


def test_pingreq_golden():
    assert Packet(fixed=FixedHeader(type=PT.PINGREQ)).encode() == b"\xc0\x00"
    assert Packet(fixed=FixedHeader(type=PT.PINGRESP)).encode() == b"\xd0\x00"


def test_connack_v4_golden():
    p = Packet(fixed=FixedHeader(type=PT.CONNACK), session_present=True,
               reason_code=0)
    assert p.encode() == b"\x20\x02\x01\x00"


def test_publish_v5_with_properties_golden():
    p = Packet(fixed=FixedHeader(type=PT.PUBLISH), protocol_version=5,
               topic="t", payload=b"x",
               properties=Properties(payload_format=1))
    assert p.encode() == b"\x30\x07\x00\x01t\x02\x01\x01x"


# ---------------------------------------------------------------------------
# Roundtrips
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("version", [3, 4, 5])
def test_connect_full_roundtrip(version):
    p = Packet(fixed=FixedHeader(type=PT.CONNECT), protocol_version=version,
               clean_start=False, keepalive=30, client_id="cl1",
               username=b"user", password=b"pw",
               username_flag=True, password_flag=True,
               will=Will(topic="w/t", payload=b"gone", qos=1, retain=True))
    if version == 5:
        p.properties = Properties(session_expiry=120, receive_maximum=5)
        p.will.properties = Properties(will_delay=9, message_expiry=44)
    q = roundtrip(p)
    assert q.client_id == "cl1"
    assert q.keepalive == 30
    assert q.username == b"user" and q.password == b"pw"
    assert q.will is not None
    assert (q.will.topic, q.will.payload, q.will.qos, q.will.retain) == \
        ("w/t", b"gone", 1, True)
    if version == 5:
        assert q.properties.session_expiry == 120
        assert q.properties.receive_maximum == 5
        assert q.will.properties.will_delay == 9


def test_connect_v3_protocol_name():
    p = Packet(fixed=FixedHeader(type=PT.CONNECT), protocol_version=3,
               client_id="x", clean_start=True)
    wire = p.encode()
    assert b"MQIsdp" in wire
    assert roundtrip(p).protocol_version == 3


@pytest.mark.parametrize("version", [4, 5])
@pytest.mark.parametrize("qos", [0, 1, 2])
def test_publish_roundtrip(version, qos):
    p = Packet(fixed=FixedHeader(type=PT.PUBLISH, qos=qos, retain=True,
                                 dup=qos > 0),
               protocol_version=version, topic="x/y/z",
               packet_id=77 if qos else 0, payload=b"\x00\x01payload")
    if version == 5:
        p.properties = Properties(message_expiry=10, topic_alias=3,
                                  user_properties=[("k", "v")],
                                  subscription_ids=[5])
    q = roundtrip(p)
    assert q.topic == "x/y/z"
    assert q.payload == b"\x00\x01payload"
    assert q.fixed.retain and (q.fixed.dup == (qos > 0))
    if qos:
        assert q.packet_id == 77
    if version == 5:
        assert q.properties.message_expiry == 10
        assert q.properties.topic_alias == 3
        assert q.properties.user_properties == [("k", "v")]
        assert q.properties.subscription_ids == [5]


@pytest.mark.parametrize("ptype", [PT.PUBACK, PT.PUBREC, PT.PUBREL, PT.PUBCOMP])
@pytest.mark.parametrize("version", [4, 5])
def test_ack_roundtrip(ptype, version, reason=0x10):
    p = Packet(fixed=FixedHeader(type=ptype), protocol_version=version,
               packet_id=99, reason_code=reason if version == 5 else 0)
    q = roundtrip(p)
    assert q.packet_id == 99
    if version == 5:
        assert q.reason_code == reason


def test_ack_v5_short_form():
    # v5 acks with success reason omit reason code + properties entirely.
    p = Packet(fixed=FixedHeader(type=PT.PUBACK), protocol_version=5, packet_id=7)
    assert p.encode() == b"\x40\x02\x00\x07"
    q = roundtrip(p)
    assert q.packet_id == 7 and q.reason_code == 0


@pytest.mark.parametrize("version", [4, 5])
def test_subscribe_roundtrip(version):
    subs = [Subscription(filter="a/+/c", qos=2, no_local=version == 5,
                         retain_as_published=version == 5, retain_handling=1
                         if version == 5 else 0),
            Subscription(filter="#", qos=0)]
    p = Packet(fixed=FixedHeader(type=PT.SUBSCRIBE), protocol_version=version,
               packet_id=42, filters=subs)
    if version == 5:
        p.properties = Properties(subscription_ids=[9])
    q = roundtrip(p)
    assert [s.filter for s in q.filters] == ["a/+/c", "#"]
    assert q.filters[0].qos == 2
    if version == 5:
        assert q.filters[0].no_local is True
        assert q.filters[0].retain_as_published is True
        assert q.filters[0].retain_handling == 1
        assert q.filters[0].identifier == 9
        assert q.filters[1].identifier == 9


@pytest.mark.parametrize("version", [4, 5])
def test_suback_unsub_roundtrip(version):
    p = Packet(fixed=FixedHeader(type=PT.SUBACK), protocol_version=version,
               packet_id=42, reason_codes=[0, 1, 0x80])
    q = roundtrip(p)
    assert q.reason_codes == [0, 1, 0x80]

    u = Packet(fixed=FixedHeader(type=PT.UNSUBSCRIBE), protocol_version=version,
               packet_id=43, filters=[Subscription(filter="a/b")])
    qu = roundtrip(u)
    assert [s.filter for s in qu.filters] == ["a/b"]

    ua = Packet(fixed=FixedHeader(type=PT.UNSUBACK), protocol_version=version,
                packet_id=43, reason_codes=[0] if version == 5 else [])
    qua = roundtrip(ua)
    assert qua.packet_id == 43


def test_disconnect_roundtrip_v5():
    p = Packet(fixed=FixedHeader(type=PT.DISCONNECT), protocol_version=5,
               reason_code=codes.ErrServerShuttingDown.value,
               properties=Properties(reason_string="bye"))
    q = roundtrip(p)
    assert q.reason_code == 0x8B
    assert q.properties.reason_string == "bye"
    # v4 DISCONNECT is empty-bodied
    v4 = Packet(fixed=FixedHeader(type=PT.DISCONNECT), protocol_version=4)
    assert v4.encode() == b"\xe0\x00"


def test_auth_roundtrip():
    p = Packet(fixed=FixedHeader(type=PT.AUTH), protocol_version=5,
               reason_code=codes.ContinueAuthentication.value,
               properties=Properties(auth_method="SCRAM", auth_data=b"\x01"))
    q = roundtrip(p)
    assert q.reason_code == 0x18
    assert q.properties.auth_method == "SCRAM"


# ---------------------------------------------------------------------------
# Malformed / protocol-error inputs
# ---------------------------------------------------------------------------

def dec(hexstr, version=4):
    buf = bytearray(bytes.fromhex(hexstr))
    fh, body = next(parse_stream(buf))
    return Packet.decode(fh, body, protocol_version=version)


def test_connect_reserved_flag_rejected():
    # flags byte 0x03 sets reserved bit 0
    with pytest.raises(ProtocolError):
        dec("100f00044d5154540403003c0003616263")


def test_connect_bad_protocol_name():
    with pytest.raises(ProtocolError) as ei:
        dec("100f0004514d54540402003c0003616263")  # "QMTT"
    assert ei.value.code == codes.ErrUnsupportedProtocolVersion


def test_connect_will_qos_without_flag():
    # will qos bits set but will flag clear (flags 0x18)
    with pytest.raises(ProtocolError):
        dec("100f00044d5154540418003c0003616263")


def test_publish_qos0_with_packet_id_is_just_payload():
    # qos0 publish: no packet-id field; bytes after topic are payload
    p = dec("300700036162630102")  # topic "abc", payload 0x0102
    assert p.topic == "abc" and p.payload == b"\x01\x02"


def test_publish_qos_nonzero_packet_id_zero():
    with pytest.raises(ProtocolError):
        dec("32070003616263000041")  # qos1, packet id 0


def test_publish_wildcard_topic_invalid():
    p = Packet(fixed=FixedHeader(type=PT.PUBLISH), topic="a/+/b")
    with pytest.raises(ProtocolError) as ei:
        p.validate_publish()
    assert ei.value.code == codes.ErrTopicNameInvalid
    p2 = Packet(fixed=FixedHeader(type=PT.PUBLISH), topic="")
    with pytest.raises(ProtocolError):
        p2.validate_publish()


def test_subscribe_no_filters_rejected():
    with pytest.raises(ProtocolError):
        dec("82020001")


def test_subscribe_missing_options_byte():
    with pytest.raises(MalformedPacketError):
        dec("820700010003612f62")  # filter present, options byte absent


def test_subscribe_reserved_option_bits_v5():
    with pytest.raises(MalformedPacketError):
        dec("820900010000036162634f", version=5)  # options 0x4F has bit6 set


def test_unsubscribe_no_filters_rejected():
    with pytest.raises(ProtocolError):
        dec("a2020001")


def test_properties_invalid_for_packet_type():
    # TOPIC_ALIAS (0x23) inside CONNECT properties is invalid
    with pytest.raises(MalformedPacketError):
        dec("101200044d515454050200000323000100026964", version=5)


def test_properties_duplicate_rejected():
    # PUBLISH v5 with payload_format twice
    with pytest.raises(MalformedPacketError):
        dec("3009000174040101010178", version=5)


def test_parse_stream_partial_and_multiple():
    a = Packet(fixed=FixedHeader(type=PT.PINGREQ)).encode()
    b = Packet(fixed=FixedHeader(type=PT.PUBLISH), topic="t", payload=b"p").encode()
    buf = bytearray(a + b[:3])
    frames = list(parse_stream(buf))
    assert len(frames) == 1 and frames[0][0].type == PT.PINGREQ
    buf.extend(b[3:])
    frames = list(parse_stream(buf))
    assert len(frames) == 1 and frames[0][0].type == PT.PUBLISH


def test_parse_stream_max_packet_size():
    big = Packet(fixed=FixedHeader(type=PT.PUBLISH), topic="t",
                 payload=b"x" * 100).encode()
    with pytest.raises(ProtocolError) as ei:
        list(parse_stream(bytearray(big), max_packet_size=50))
    assert ei.value.code == codes.ErrPacketTooLarge


def test_packet_copy_is_deep():
    p = Packet(fixed=FixedHeader(type=PT.PUBLISH, qos=1), topic="t",
               payload=b"p", packet_id=5,
               properties=Properties(user_properties=[("a", "b")]),
               filters=[Subscription(filter="f", qos=1)])
    q = p.copy()
    q.properties.user_properties.append(("c", "d"))
    q.filters[0].qos = 2
    q.fixed.qos = 0
    assert p.properties.user_properties == [("a", "b")]
    assert p.filters[0].qos == 1
    assert p.fixed.qos == 1


def test_connack_v3_downgrade():
    assert codes.connack_for_version(codes.ErrNotAuthorized, 4) == 0x05
    assert codes.connack_for_version(codes.ErrBadUsernameOrPassword, 3) == 0x04
    assert codes.connack_for_version(codes.ErrNotAuthorized, 5) == 0x87
    assert codes.connack_for_version(codes.Success, 4) == 0x00


# ---------------------------------------------------------------------------
# Regressions from review: stricter spec conformance
# ---------------------------------------------------------------------------

def test_subscribe_qos3_malformed():
    with pytest.raises(MalformedPacketError):
        Subscription.from_options_byte("a", 0x03, False)
    with pytest.raises(MalformedPacketError):
        Subscription.from_options_byte("a", 0x03, True)


def test_connect_password_without_username_v4_rejected():
    # flags 0x42: clean + password, no username [MQTT-3.1.2-22]
    with pytest.raises(ProtocolError):
        dec("101300044d5154540442003c000361626300027077")


def test_connect_password_without_username_v5_allowed():
    p = Packet(fixed=FixedHeader(type=PT.CONNECT), protocol_version=5,
               client_id="c", clean_start=True, password=b"pw",
               password_flag=True)
    assert roundtrip(p).password == b"pw"


def test_auth_rejected_pre_v5():
    with pytest.raises(ProtocolError):
        dec("f000", version=4)


def test_publish_dup_qos0_tolerated():
    # dup with qos 0 violates the sender requirement [MQTT-3.3.1-2] but
    # the receive side tolerates it, like the reference (tpackets.go
    # TPublishDup decodes cleanly)
    p = dec("38050003616263")  # dup=1, qos=0
    assert p.fixed.dup and p.fixed.qos == 0 and p.topic == "abc"


def test_publish_empty_topic_with_alias_ok_v5():
    p = Packet(fixed=FixedHeader(type=PT.PUBLISH), protocol_version=5,
               topic="", properties=Properties(topic_alias=4))
    p.validate_publish()  # must not raise
    with pytest.raises(ProtocolError):
        Packet(fixed=FixedHeader(type=PT.PUBLISH), protocol_version=5,
               topic="").validate_publish()
