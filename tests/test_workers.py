"""Worker-pool cross-worker semantics (ADR 005 pool, ADR 021 wiring).

This box has one core, so these tests assert CORRECTNESS of the
SO_REUSEPORT pool (cross-worker delivery, retained convergence, $share
exactly-once, takeover), not speedup. Since ADR 021 the workers are
cluster nodes meshed over unix-domain bridge links — the pool runs
in-process here: N Broker instances built by the same
build_worker_broker wiring the subprocess pool uses, on distinct
loopback ports so each test can target a specific worker.

Publish forwarding is route-driven now (the ADR-005 bus broadcast
blindly), so tests hop the route/ledger convergence barriers
explicitly (await_routes / poll_until) instead of sleeping.
"""

import asyncio
import contextlib
import os
import time

from maxmq_tpu.broker.workers import (await_routes, inprocess_pool,
                                      worker_sock)
from maxmq_tpu.mqtt_client import MQTTClient


@contextlib.asynccontextmanager
async def running_pool(n: int = 2):
    async with inprocess_pool(
            n, link_dir=f"/tmp/maxmq-test-pool-{os.getpid()}") as out:
        yield out


async def poll_until(pred, timeout: float = 5.0,
                     what: str = "condition") -> None:
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() >= deadline:
            raise TimeoutError(f"{what} never converged")
        await asyncio.sleep(0.01)


def share_members(broker, key):
    return broker.cluster.routes.shares.members_for(key)


async def test_cross_worker_delivery():
    async with running_pool(2) as (brokers, ports):
        sub = MQTTClient("w-sub")
        await sub.connect("127.0.0.1", ports[0])
        await sub.subscribe("pool/+/x")
        pub = MQTTClient("w-pub")
        await pub.connect("127.0.0.1", ports[1])   # OTHER worker
        await await_routes(brokers[1], "pool/a/x")
        await pub.publish("pool/a/x", b"crossed")
        m = await sub.next_message(5)
        assert m.payload == b"crossed"
        # reverse direction too
        sub2 = MQTTClient("w-sub2")
        await sub2.connect("127.0.0.1", ports[1])
        await sub2.subscribe("pool/#")
        pub2 = MQTTClient("w-pub2")
        await pub2.connect("127.0.0.1", ports[0])
        await await_routes(brokers[0], "pool/b/x")
        await pub2.publish("pool/b/x", b"back")
        m = await sub2.next_message(5)
        assert m.payload == b"back"
        for c in (sub, sub2, pub, pub2):
            await c.disconnect()


async def test_retained_converges_across_workers():
    async with running_pool(2) as (brokers, ports):
        pub = MQTTClient("r-pub")
        await pub.connect("127.0.0.1", ports[0])
        await pub.publish("pool/ret/x", b"kept", retain=True)
        # retained publishes flood every link; wait for the fan-in
        await poll_until(
            lambda: brokers[1].cluster.forwards_delivered >= 1,
            what="retained forward")
        fresh = MQTTClient("r-fresh")
        await fresh.connect("127.0.0.1", ports[1])   # OTHER worker
        await fresh.subscribe("pool/ret/#")
        m = await fresh.next_message(5)
        assert m.payload == b"kept" and m.retain
        await pub.disconnect()
        await fresh.disconnect()


async def test_shared_group_exactly_once_across_workers():
    async with running_pool(2) as (brokers, ports):
        key = ("g", "$share/g/pool/sh")
        m0 = MQTTClient("s-m0")
        await m0.connect("127.0.0.1", ports[0])
        await m0.subscribe("$share/g/pool/sh", qos=0)
        m1 = MQTTClient("s-m1")
        await m1.connect("127.0.0.1", ports[1])
        await m1.subscribe("$share/g/pool/sh", qos=0)
        # both workers' ledgers must agree on the membership before
        # publishing, or the divergence window double/zero-delivers
        await poll_until(
            lambda: set(share_members(brokers[0], key)) == {"w0", "w1"}
            and set(share_members(brokers[1], key)) == {"w0", "w1"},
            what="share ledger")
        await await_routes(brokers[1], "pool/sh")
        pub = MQTTClient("s-pub")
        await pub.connect("127.0.0.1", ports[1])
        n = 10
        for i in range(n):
            await pub.publish("pool/sh", f"m{i}".encode())
        await asyncio.sleep(0.5)
        got0, got1 = m0.messages.qsize(), m1.messages.qsize()
        # exactly-once globally: every message delivered to exactly one
        # group member across the whole pool
        assert got0 + got1 == n, (got0, got1)
        for c in (m0, m1, pub):
            await c.disconnect()


async def test_cross_worker_takeover():
    # [MQTT-3.1.4-2]: a session established on worker 1 must terminate
    # a live session with the same client id on worker 0
    async with running_pool(2) as (brokers, ports):
        first = MQTTClient("dup-id")
        await first.connect("127.0.0.1", ports[0])
        second = MQTTClient("dup-id")
        await second.connect("127.0.0.1", ports[1])
        await first.wait_closed(timeout=5)   # old session taken over
        old = brokers[0].clients.get("dup-id")
        assert old is None or old.closed or old.taken_over
        await second.ping()                  # new session healthy
        await second.disconnect()


async def test_shared_owner_skips_offline_members():
    # a worker whose only group member went offline must cede ownership
    # so the live member on the other worker still receives
    async with running_pool(2) as (brokers, ports):
        key = ("g", "$share/g/pool/so")
        m0 = MQTTClient("so-m0", clean_start=False, session_expiry=300,
                        version=5)
        await m0.connect("127.0.0.1", ports[0])
        await m0.subscribe("$share/g/pool/so", qos=0)
        m1 = MQTTClient("so-m1")
        await m1.connect("127.0.0.1", ports[1])
        await m1.subscribe("$share/g/pool/so", qos=0)
        await poll_until(
            lambda: set(share_members(brokers[0], key)) == {"w0", "w1"},
            what="share ledger")
        await m0.close()                     # offline; session persists
        # the ledger counts LIVE members only: w0 must cede everywhere
        await poll_until(
            lambda: share_members(brokers[0], key) == ["w1"]
            and share_members(brokers[1], key) == ["w1"],
            what="offline member ceding ownership")
        await await_routes(brokers[0], "pool/so")
        pub = MQTTClient("so-pub")
        await pub.connect("127.0.0.1", ports[0])
        for i in range(5):
            await pub.publish("pool/so", f"m{i}".encode())
        got = 0
        for _ in range(5):
            await m1.next_message(5)
            got += 1
        assert got == 5                      # live member got them all
        await m1.disconnect()
        await pub.disconnect()


async def test_qos1_delivery_across_workers():
    async with running_pool(2) as (brokers, ports):
        sub = MQTTClient("q-sub")
        await sub.connect("127.0.0.1", ports[0])
        await sub.subscribe(("pool/q1", 1))
        pub = MQTTClient("q-pub")
        await pub.connect("127.0.0.1", ports[1])
        await await_routes(brokers[1], "pool/q1")
        await pub.publish("pool/q1", b"ackd", qos=1)
        m = await sub.next_message(5)
        assert m.payload == b"ackd"
        assert m.qos == 1
        await sub.disconnect()
        await pub.disconnect()


async def test_worker_sock_layout():
    """The mesh sockets live inside the pool dir, one per worker —
    the layout the subprocess pool, the in-process pool, and the
    sibling peer specs must all agree on."""
    assert worker_sock("/tmp/p", 3) == "/tmp/p/w3.sock"


async def test_pool_workers_share_one_matcher_service(tmp_path):
    """The flagship composition (ADR 005 + 006 + 021): N pool workers,
    ONE chip-owning matcher service. Each worker forwards its own
    clients' subscription ops; cross-worker publishes ride the bridge
    mesh and each worker's matches route through the shared service."""
    from maxmq_tpu.matching.service import (MatcherService,
                                            attach_matcher_service)

    path = str(tmp_path / "m.sock")
    svc = MatcherService(path)
    await svc.start()
    try:
        async with running_pool(2) as (brokers, ports):
            for b in brokers:
                await attach_matcher_service(b, path)
            sub = MQTTClient("ps-sub")
            await sub.connect("127.0.0.1", ports[0])
            await sub.subscribe("svcpool/+/x")
            pub = MQTTClient("ps-pub")
            await pub.connect("127.0.0.1", ports[1])   # OTHER worker
            await await_routes(brokers[1], "svcpool/a/x")
            await pub.publish("svcpool/a/x", b"via-svc")
            m = await sub.next_message(5)
            assert m.payload == b"via-svc"
            # both workers' matching went through the one service
            assert svc.matches_served >= 1
            assert svc.subs_applied >= 1
            await sub.disconnect()
            await pub.disconnect()
    finally:
        await svc.close()
