"""ADR-012 overload-protection ladder suite: byte-accounted outbound
queues, oldest-first QoS0 shedding, the writer stall deadline, CONNECT
admission control (token bucket + half-open cap), global load-shed
watermarks with recovery, and the QoS>0 queue-full rollback fixes — all
driven deterministically through the fault registry (``client.write`` /
``listener.accept`` sites) against a real broker on a real TCP socket.
"""

import asyncio
import time

import pytest

from test_broker_system import connect, running_broker

from maxmq_tpu import faults
from maxmq_tpu.broker.client import OutboundQueue
from maxmq_tpu.broker.overload import TokenBucket, top_offenders
from maxmq_tpu.metrics import Registry, register_broker_metrics
from maxmq_tpu.mqtt_client import MQTTError
from maxmq_tpu.protocol.codec import PacketType as PT
from maxmq_tpu.protocol.packets import Packet
from maxmq_tpu.protocol.codec import FixedHeader

CONNECT_REFUSED = (MQTTError, ConnectionError, OSError,
                   asyncio.TimeoutError, asyncio.IncompleteReadError)


@pytest.fixture(autouse=True)
def clean_faults():
    faults.clear()
    yield
    faults.clear()


async def try_connect(broker, client_id: str, version: int = 4):
    """connect() with a short handshake deadline: admission-control
    tests expect the refused socket to surface quickly."""
    from maxmq_tpu.mqtt_client import MQTTClient
    c = MQTTClient(client_id=client_id, version=version)
    await c.connect("127.0.0.1", broker.test_port, timeout=2.0)
    return c


def stall_writer(client_id: str, delay_s: float = 30.0,
                 count: int = -1) -> None:
    """Deterministically stall ONE client's writer via the keyed
    client.write fault site (hang mode = awaited sleep in the loop)."""
    faults.arm(f"{faults.CLIENT_WRITE}#{client_id}", "hang",
               count=count, delay_s=delay_s)


async def poll(predicate, timeout: float = 5.0, what: str = ""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        await asyncio.sleep(0.05)
    raise AssertionError(f"condition not reached in {timeout}s: {what}")


# -- units: token bucket + byte-accounted queue ------------------------


def test_token_bucket_burst_and_refill():
    bucket = TokenBucket(rate=10.0, burst=2)
    t0 = time.monotonic()
    assert bucket.allow(t0) and bucket.allow(t0)
    assert not bucket.allow(t0)            # burst exhausted
    # +0.15s at 10/s refills 1.5 tokens: the margin keeps the assert
    # robust to float rounding of (t0 + dt) - t0 at large t0
    assert bucket.allow(t0 + 0.15)         # a token refilled
    assert not bucket.allow(t0 + 0.15)     # only ~0.5 left
    assert TokenBucket(rate=0.0).allow()   # rate 0 = unlimited


def _pub0_wire(payload: bytes) -> bytes:
    return bytes([0x30, len(payload) + 5, 0, 3]) + b"t/x" + payload


def test_outbound_queue_drops_oldest_qos0_only():
    q = OutboundQueue(maxsize=16)
    ack = bytes((PT.PUBACK << 4, 2, 0, 1))          # never droppable
    qos1 = Packet(fixed=FixedHeader(type=PT.PUBLISH, qos=1),
                  topic="t/x", payload=b"keep", packet_id=7)
    q.put_nowait(_pub0_wire(b"old1"), 9)
    q.put_nowait(ack, 4)
    q.put_nowait(qos1, 40)
    q.put_nowait(_pub0_wire(b"old2"), 9)
    q.put_nowait(_pub0_wire(b"new"), 8)
    assert q.bytes == 70
    dropped, freed = q.drop_oldest_qos0(15)
    assert dropped == [_pub0_wire(b"old1"), _pub0_wire(b"old2")]
    assert freed == 18
    assert q.bytes == 52
    # survivors keep their order: ack, qos1 publish, newest qos0
    assert q.get_nowait() == ack
    assert q.get_nowait() is qos1
    assert q.get_nowait() == _pub0_wire(b"new")
    assert q.bytes == 0
    # an all-protected queue frees nothing
    q.put_nowait(qos1, 40)
    assert q.drop_oldest_qos0(100) == ([], 0)


def test_top_offenders_is_bounded_and_sorted():
    class C:
        def __init__(self, cid, n, shed=0):
            self.id, self.dropped_msgs, self.dropped_bytes = cid, n, n * 10
            self.drops_by_reason = {"shed": shed} if shed else {}
    clients = [C(f"c{i}", i) for i in range(20)]
    # a healthy client hit only by GLOBAL sheds must not outrank (or
    # even appear above) the slow consumers that caused the overload
    clients.append(C("victim", 100, shed=100))
    rows = top_offenders(clients)
    assert len(rows) == 8                           # cardinality bound
    assert rows[0]["client"] == "c19" and rows[0]["dropped"] == 19
    assert [r["dropped"] for r in rows] == sorted(
        (r["dropped"] for r in rows), reverse=True)
    assert all(r["client"] != "victim" for r in rows)


# -- admission control -------------------------------------------------


async def test_connect_storm_token_bucket_refuses_sockets():
    async with running_broker(connect_rate=0.001,
                              connect_burst=2) as broker:
        c1 = await connect(broker, "a")
        c2 = await connect(broker, "b")
        for i in range(3):                  # bucket exhausted: refused
            with pytest.raises(CONNECT_REFUSED):
                await try_connect(broker, f"storm{i}")
        assert broker.overload.connects_refused >= 3
        await c1.ping()                     # admitted clients unharmed
        await c1.disconnect()
        await c2.disconnect()


async def test_half_open_handshake_cap():
    async with running_broker(connect_half_open_max=1) as broker:
        # a socket that never sends CONNECT occupies the only slot
        _r, w = await asyncio.open_connection("127.0.0.1",
                                              broker.test_port)
        await asyncio.sleep(0.1)
        with pytest.raises(CONNECT_REFUSED):
            await try_connect(broker, "x")
        assert broker.overload.half_open_refused >= 1
        w.close()
        await asyncio.sleep(0.2)            # slot settles on EOF
        c = await connect(broker, "y")      # admitted again
        await c.disconnect()


async def test_listener_accept_fault_refuses_socket():
    async with running_broker() as broker:
        faults.arm(faults.LISTENER_ACCEPT, "raise", count=1)
        with pytest.raises(CONNECT_REFUSED):
            await try_connect(broker, "nope")
        assert broker.overload.connects_refused == 1
        c = await connect(broker, "yep")    # fault self-disarmed
        await c.disconnect()


# -- slow-consumer policy: byte budget + stall deadline ----------------


async def test_byte_budget_sheds_oldest_keeps_newest():
    async with running_broker(client_byte_budget=2048) as broker:
        slow = await connect(broker, "slow")
        await slow.subscribe("fire/#")
        stall_writer("slow", delay_s=0.15)
        pub = await connect(broker, "pub")
        for i in range(10):
            await pub.publish("fire/x", b"%02d" % i + b"z" * 400)
        cl = broker.clients.get("slow")
        await poll(lambda: cl.drops_by_reason.get("byte_budget", 0) > 0,
                   what="byte-budget drops recorded")
        assert broker.overload.budget_drops > 0
        assert broker.info.messages_dropped > 0
        assert cl.dropped_bytes > 0
        # oldest-first: the NEWEST message survives the shed and lands
        got = []
        while True:
            try:
                got.append(await slow.next_message(timeout=3.0))
            except asyncio.TimeoutError:
                break
        assert got and got[-1].payload.startswith(b"09")
        assert len(got) < 10                # and some were truly shed
        await pub.disconnect()
        await slow.disconnect()


async def test_stalled_writer_disconnected_with_quota_exceeded():
    async with running_broker(stall_deadline_ms=300) as broker:
        healthy = await connect(broker, "healthy")
        slow = await connect(broker, "slow", version=5)
        await slow.subscribe("s/#")
        cl = broker.clients.get("slow")
        stall_writer("slow", delay_s=30.0)
        pub = await connect(broker, "pub")
        for _ in range(4):
            await pub.publish("s/t", b"x" * 64)
        await slow.wait_closed(timeout=5)
        assert slow.disconnect_packet is not None
        assert slow.disconnect_packet.reason_code == 0x97  # QuotaExceeded
        assert broker.overload.stalled_disconnects == 1
        assert cl.drops_by_reason.get("stall") == 1
        await healthy.ping()                # broker live throughout
        await healthy.disconnect()
        await pub.disconnect()


async def test_burst_cap_keeps_wedged_backlog_accounted():
    """The writer's greedy burst is byte-capped: a consumer whose
    transport never drains must keep its backlog in the ACCOUNTED
    queue (visible to the stall detector and global watermarks), not
    silently de-accounted into the transport buffer."""
    from maxmq_tpu.broker import Broker, BrokerOptions, Capabilities
    from maxmq_tpu.broker.client import Client

    broker = Broker(BrokerOptions(
        capabilities=Capabilities(sys_topic_interval=0)))

    class BlockedWriter:
        def write(self, data): pass
        async def drain(self): await asyncio.Event().wait()
        def close(self): pass
        def get_extra_info(self, name, default=None): return default

    cl = Client(broker, None, BlockedWriter())
    cl.id = "wedge"
    wire = bytes([0x30, 0x7F]) + b"x" * 30000   # QoS0 PUBLISH-typed
    for _ in range(5):
        assert cl.send_wire(wire)
    assert broker.overload.queued_bytes == 5 * len(wire)
    cl.start()
    await asyncio.sleep(0.2)
    # the burst stopped at BURST_BYTES and parked on drain(): the
    # remaining backlog is still on both byte ledgers
    assert cl.outbound.bytes == 2 * len(wire)
    assert broker.overload.queued_bytes == cl.outbound.bytes
    cl._writer_task.cancel()


async def test_dead_writer_recorded_not_silently_swallowed():
    """Satellite: _drain used to swallow ConnectionError silently —
    the failure must be recorded so the stall detector acts on it."""
    async with running_broker(stall_deadline_ms=10_000) as broker:
        c = await connect(broker, "w")
        cl = broker.clients.get("w")

        class DeadWriter:
            """Delegates to the real transport but fails every drain —
            a peer whose receive direction died under the broker."""
            def __init__(self, real): self._real = real
            def __getattr__(self, name): return getattr(self._real, name)
            async def drain(self): raise ConnectionResetError("peer gone")
        cl.writer = DeadWriter(cl.writer)
        await cl._drain()
        assert cl.write_error and "peer gone" in cl.write_error
        # housekeeping treats a dead writer as an immediate stall even
        # far below the 10s no-progress deadline
        await poll(lambda: broker.overload.stalled_disconnects == 1,
                   what="dead writer disconnected")
        await c.wait_closed(timeout=5)


# -- QoS>0 queue-full rollback (the send()-False leak fixes) -----------


async def test_qos_drop_rolls_back_quota_and_inflight():
    async with running_broker() as broker:
        s = await connect(broker, "s1")
        await s.subscribe(("q/#", 1))
        cl = broker.clients.get("s1")
        cl.send = lambda packet: False      # every delivery refused
        p = await connect(broker, "p1")
        await p.publish("q/a", b"x", qos=1)
        await poll(lambda: broker.overload.qos_drops == 1,
                   what="qos_drop counted")
        assert len(cl.inflight) == 0        # no stale inflight entry
        assert cl.inflight.send_quota == cl.inflight.maximum_send
        assert broker.info.inflight == 0
        assert broker.info.messages_dropped == 0   # distinct reason
        await p.disconnect()
        await s.disconnect()


async def test_release_held_drop_rolls_back():
    async with running_broker(receive_maximum=1) as broker:
        s = await connect(broker, "s1")
        await s.subscribe(("h/#", 1))
        cl = broker.clients.get("s1")
        stall_writer("s1", delay_s=0.3, count=1)
        p = await connect(broker, "p1")
        await p.publish("h/1", b"m1", qos=1)   # takes the only quota slot
        await p.publish("h/2", b"m2", qos=1)   # parks on held_pids
        await poll(lambda: len(cl.held_pids) == 1, what="m2 parked")
        cl.send = lambda packet: False      # refuse the held release
        msg = await s.next_message(timeout=5)  # m1 lands; client PUBACKs
        assert msg.payload == b"m1"
        await poll(lambda: broker.overload.qos_drops == 1,
                   what="held release rolled back")
        assert len(cl.inflight) == 0
        assert cl.inflight.send_quota == 1  # quota returned
        assert not cl.held_pids
        await p.disconnect()
        await s.disconnect()


# -- keepalive + takeover under a wedged outbound path -----------------


async def test_keepalive_enforced_while_writer_stalled():
    async with running_broker(keepalive_grace=0.2,
                              stall_deadline_ms=0) as broker:
        c = await connect(broker, "ka", keepalive=1)
        await c.subscribe("ka/#")
        stall_writer("ka", delay_s=30.0)
        p = await connect(broker, "pub", keepalive=0)
        await p.publish("ka/t", b"wedge")
        # no PINGREQ from "ka": the keepalive deadline still fires even
        # though its writer is wedged mid-delivery
        await c.wait_closed(timeout=5)
        await poll(lambda: broker.clients.get("ka") is None
                   or broker.clients.get("ka").closed,
                   what="keepalive closed the stalled client")
        await p.disconnect()


async def test_takeover_with_full_outbound_resends_inflight_only():
    """Session takeover while the old connection's outbound queue is
    full: resume must re-deliver what is in INFLIGHT, not the overflow
    the budget refused (which was rolled back, not left half-queued)."""
    async with running_broker(client_byte_budget=600) as broker:
        c1 = await connect(broker, "tk", clean_start=False)
        await c1.subscribe(("tk/#", 1))
        stall_writer("tk", delay_s=30.0)
        pub = await connect(broker, "pub")
        for i in range(6):
            await pub.publish("tk/t", b"m%d" % i + b"f" * 180, qos=1)
        cl = broker.clients.get("tk")
        await poll(lambda: broker.overload.qos_drops > 0,
                   what="overflow rolled back")
        kept = {p.payload[:2] for p in cl.inflight.all()}
        assert 0 < len(kept) < 6
        faults.disarm(f"{faults.CLIENT_WRITE}#tk")   # new writer healthy
        dropped = {b"m%d" % i for i in range(6)} - kept

        async def drain_resumed(c):
            got = set()
            while True:
                try:
                    got.add((await c.next_message(timeout=1.0)).payload[:2])
                except asyncio.TimeoutError:
                    return got

        c2 = await connect(broker, "tk", clean_start=False)
        assert c2.connack.session_present
        got = await drain_resumed(c2)
        # only inflight is redelivered — never the rolled-back overflow
        assert got and got <= kept and not (got & dropped)
        # whatever the resend burst's own budget deferred stays inflight
        # and lands on the NEXT resume (it was parked, not lost)
        remaining = kept - got
        await c2.disconnect()
        if remaining:
            c3 = await connect(broker, "tk", clean_start=False)
            got2 = await drain_resumed(c3)
            assert remaining <= got2 and not (got2 & dropped)
            await c3.disconnect()
        await pub.disconnect()


# -- global watermarks: shed, defer retained, recover ------------------


async def test_load_shed_watermarks_defer_retained_and_recover():
    async with running_broker(broker_byte_budget=4096,
                              overload_high_water=0.5,
                              overload_low_water=0.25,
                              stall_deadline_ms=0) as broker:
        slow = await connect(broker, "slow")
        await slow.subscribe("fire/#")
        stall_writer("slow", delay_s=30.0)
        healthy = await connect(broker, "healthy")
        await healthy.subscribe("live/#")
        pub = await connect(broker, "pub")
        await pub.publish("ret/1", b"parked", retain=True)
        for _ in range(8):                  # cross the high-water mark
            await pub.publish("fire/x", b"z" * 600)
        await poll(lambda: broker.overload.shedding,
                   what="high water entered shedding")
        assert broker.overload.sheds == 1
        # shedding: QoS0 fan-out to the HEALTHY subscriber is shed too
        await pub.publish("live/a", b"shed-me")
        with pytest.raises(asyncio.TimeoutError):
            await healthy.next_message(timeout=0.3)
        assert broker.overload.shed_messages >= 1
        # retained delivery defers instead of piling on
        await healthy.subscribe("ret/#")
        assert broker.overload.deferred_retained == 1
        with pytest.raises(asyncio.TimeoutError):
            await healthy.next_message(timeout=0.3)
        # the slow consumer goes away: its queued bytes release and the
        # broker recovers below the low-water mark
        await slow.close()
        await poll(lambda: not broker.overload.shedding,
                   what="recovered below low water")
        assert broker.overload.recoveries >= 1
        # deferred retained lands after recovery (housekeeping drain)
        msg = await healthy.next_message(timeout=5)
        assert (msg.topic, msg.payload, msg.retain) == \
            ("ret/1", b"parked", True)
        # and live fan-out flows again
        await pub.publish("live/b", b"back")
        assert (await healthy.next_message(timeout=5)).payload == b"back"
        await pub.disconnect()
        await healthy.disconnect()


async def test_deferred_retained_survives_offline_resume():
    """A persistent session whose retained delivery was deferred by
    shedding, then disconnected before recovery, must still get the
    retained message on resume — a resumed session never re-sends
    SUBSCRIBE, so a discarded deferral would lose it permanently."""
    async with running_broker(broker_byte_budget=4096,
                              overload_high_water=0.5,
                              overload_low_water=0.25,
                              stall_deadline_ms=0) as broker:
        slow = await connect(broker, "slow")
        await slow.subscribe("fire/#")
        stall_writer("slow", delay_s=30.0)
        pub = await connect(broker, "pub")
        await pub.publish("ret/1", b"parked", retain=True)
        for _ in range(8):
            await pub.publish("fire/x", b"z" * 600)
        await poll(lambda: broker.overload.shedding, what="shedding")
        durable = await connect(broker, "durable", clean_start=False)
        await durable.subscribe(("ret/#", 1))
        assert broker.overload.deferred_retained == 1
        await durable.close()           # offline before recovery
        await slow.close()              # wedge releases -> recovery
        await poll(lambda: not broker.overload.shedding, what="recovery")
        await asyncio.sleep(1.2)        # a drain tick passes while offline
        resumed = await connect(broker, "durable", clean_start=False)
        assert resumed.connack.session_present
        msg = await resumed.next_message(timeout=5)
        assert (msg.topic, msg.payload, msg.retain) == \
            ("ret/1", b"parked", True)
        await resumed.disconnect()
        await pub.disconnect()


# -- observability -----------------------------------------------------


async def test_overload_metrics_and_sys_tree_exposed():
    async with running_broker(client_byte_budget=512) as broker:
        reg = Registry()
        register_broker_metrics(reg, broker)
        slow = await connect(broker, "offender")
        await slow.subscribe("m/#")
        stall_writer("offender", delay_s=30.0)
        pub = await connect(broker, "pub")
        for _ in range(6):
            await pub.publish("m/x", b"y" * 300)
        cl = broker.clients.get("offender")
        await poll(lambda: cl.dropped_msgs > 0, what="drops recorded")
        text = reg.expose()
        assert "maxmq_broker_overload_queued_bytes" in text
        assert "maxmq_broker_overload_shedding 0" in text
        assert "maxmq_broker_overload_budget_drops_total" in text
        assert "maxmq_broker_overload_qos_drops_total" in text
        assert ('maxmq_broker_overload_connects_refused_total'
                '{reason="rate"} 0') in text
        assert ('maxmq_broker_client_dropped_messages_total'
                '{client="offender"}') in text
        sys_entries = broker._sys_overload_entries()
        assert sys_entries["$SYS/broker/overload/budget_drops"] > 0
        assert "offender" in \
            sys_entries["$SYS/broker/clients/top_dropped"]
        await pub.disconnect()


# -- the acceptance bar: the whole ladder, end to end ------------------


async def test_overload_ladder_end_to_end():
    """Stalled subscriber + CONNECT storm: the broker stays live for
    healthy clients, disconnects the stalled consumer within the stall
    deadline, sheds at the high-water mark, and recovers below the
    low-water mark — all visible through maxmq_broker_overload_*."""
    async with running_broker(broker_byte_budget=4096,
                              overload_high_water=0.5,
                              overload_low_water=0.25,
                              stall_deadline_ms=400,
                              connect_rate=0.001,
                              connect_burst=3) as broker:
        reg = Registry()
        register_broker_metrics(reg, broker)
        healthy = await connect(broker, "healthy")     # token 1
        await healthy.subscribe("live/#")
        slow = await connect(broker, "slowpoke", version=5)  # token 2
        await slow.subscribe("firehose/#")
        stall_writer("slowpoke", delay_s=30.0)
        pub = await connect(broker, "pub")             # token 3
        t_stall = time.monotonic()
        for _ in range(8):
            await pub.publish("firehose/x", b"z" * 600)
        await poll(lambda: broker.overload.shedding,
                   what="shedding at high water")
        # CONNECT storm: bucket empty, sockets refused outright
        for i in range(4):
            with pytest.raises(CONNECT_REFUSED):
                await try_connect(broker, f"storm{i}")
        assert broker.overload.connects_refused >= 4
        await healthy.ping()        # live for healthy clients throughout
        # stalled consumer disconnected within the deadline (+1s tick)
        await slow.wait_closed(timeout=5)
        assert time.monotonic() - t_stall < 5.0
        assert slow.disconnect_packet.reason_code == 0x97
        assert broker.overload.stalled_disconnects == 1
        # its released queue takes the broker below low water
        await poll(lambda: not broker.overload.shedding,
                   what="recovery below low water")
        await pub.publish("live/b", b"recovered")
        assert (await healthy.next_message(timeout=5)).payload \
            == b"recovered"
        text = reg.expose()
        assert "maxmq_broker_overload_sheds_total 1" in text
        assert "maxmq_broker_overload_recoveries_total" in text
        assert "maxmq_broker_overload_stalled_disconnects_total 1" in text
        assert ('maxmq_broker_overload_connects_refused_total'
                '{reason="rate"} 4') in text
        await pub.disconnect()
        await healthy.disconnect()
