"""Cluster-mode matcher: shard_map parity on the 8-device CPU mesh."""

import random

import pytest

import jax

from maxmq_tpu.matching.trie import TopicIndex
from maxmq_tpu.parallel.sharded import ShardedNFAEngine, make_mesh
from maxmq_tpu.protocol.packets import Subscription

ALPHABET = ["alpha", "beta", "gamma", "delta", "eps", "zeta"]


def random_corpus(n_filters, n_topics, seed):
    rng = random.Random(seed)

    def filt():
        depth = rng.randint(1, 6)
        levels = [rng.choice(ALPHABET) for _ in range(depth)]
        r = rng.random()
        if r < 0.3:
            levels[rng.randrange(depth)] = "+"
        elif r < 0.45:
            levels = levels[: rng.randint(1, depth)] + ["#"]
        f = "/".join(levels)
        if rng.random() < 0.15:
            f = f"$share/grp{rng.randint(0, 2)}/{f}"
        return f

    filters = [filt() for _ in range(n_filters)]
    topics = ["/".join(rng.choice(ALPHABET)
                       for _ in range(rng.randint(1, 6)))
              for _ in range(n_topics)]
    topics += ["$SYS/broker/load", "a//b", "/leading"]
    return filters, topics


def build_index(filters):
    index = TopicIndex()
    for i, f in enumerate(filters):
        index.subscribe(f"c{i}", Subscription(filter=f, qos=i % 3))
    return index


def assert_same(got, want, topic):
    assert set(got.subscriptions) == set(want.subscriptions), topic
    for cid, sub in want.subscriptions.items():
        assert got.subscriptions[cid].qos == sub.qos, (topic, cid)
    assert set(got.shared) == set(want.shared), topic
    for key, members in want.shared.items():
        assert set(got.shared[key]) == set(members), (topic, key)


@pytest.mark.parametrize("shape", [(1, 8), (2, 4), (4, 2)])
def test_sharded_parity_vs_trie(shape):
    filters, topics = random_corpus(300, 64, seed=shape[0] * 31 + shape[1])
    index = build_index(filters)
    mesh = make_mesh(shape=shape)
    engine = ShardedNFAEngine(index, mesh=mesh, width=32, max_levels=8)
    got = engine.subscribers_batch(topics)
    for topic, s in zip(topics, got):
        assert_same(s, index.subscribers(topic), topic)


def test_sharded_tracks_index_mutations():
    filters, topics = random_corpus(50, 16, seed=9)
    index = build_index(filters)
    engine = ShardedNFAEngine(index, width=32, max_levels=8)
    index.subscribe("late", Subscription(filter="alpha/#", qos=1))
    got = engine.subscribers("alpha/beta")
    assert "late" in got.subscriptions


def test_make_mesh_default_shape():
    mesh = make_mesh()
    assert mesh.devices.size == len(jax.devices())
    assert set(mesh.axis_names) == {"data", "subs"}


def test_graft_entry_single_chip():
    import numpy as np

    import __graft_entry__ as ge

    fn, example_args = ge.entry()
    counts, stream = fn(*example_args)      # stream wire format
    batch = example_args[0].shape[0]
    counts = np.asarray(counts)
    assert counts.shape == (batch,)
    assert (counts != 255).all()            # 255 = overflow sentinel;
    total = int(counts.sum())               # this corpus never overflows
    assert 0 < total <= stream.shape[0]


def test_graft_entry_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


# ----------------------------------------------------- sharded sig engine

from maxmq_tpu.parallel.sharded import ShardedSigEngine


@pytest.mark.parametrize("shape", [(1, 8), (2, 4), (4, 2)])
def test_sharded_sig_parity_vs_trie(shape):
    filters, topics = random_corpus(300, 64, seed=shape[0] * 17 + shape[1])
    index = build_index(filters)
    mesh = make_mesh(shape=shape)
    engine = ShardedSigEngine(index, mesh=mesh)
    got = engine.subscribers_batch(topics)
    for topic, g in zip(topics, got):
        assert_same(g, index.subscribers(topic), topic)


def test_sharded_sig_refresh_and_fallback():
    filters, topics = random_corpus(100, 16, seed=9)
    index = build_index(filters)
    engine = ShardedSigEngine(index, mesh=make_mesh(shape=(2, 4)))
    index.subscribe("late", Subscription(filter="alpha/#", qos=1))
    got = engine.subscribers("alpha/beta")
    assert "late" in got.subscriptions
    # deep topic -> CPU fallback, still exact
    deep = "/".join(["alpha"] * 80)
    index.subscribe("deepc", Subscription(filter="/".join(["alpha"] * 80)))
    got = engine.subscribers(deep)
    assert_same(got, index.subscribers(deep), deep)


def test_sharded_sig_padding_words_cannot_fire():
    """Padding word slots must point at the all-zero-coefficient padding
    group (signature deterministically 0, never the 0xFFFFFFFF poison
    plane) — a real group's signature can adversarially equal the poison
    and emit row ids past the shard's row tables."""
    import numpy as np

    filters, _topics = random_corpus(60, 0, seed=3)
    index = build_index(filters)
    engine = ShardedSigEngine(index, mesh=make_mesh(shape=(1, 8)))
    _v, shards, dev, fn, _d, _ue, _dp, _ck = engine._state
    assert fn is not None
    topo = np.asarray(dev[0])           # [sp, G, D] coefficients
    dc = np.asarray(dev[1])             # [sp, G] depth coefficients
    grp = np.asarray(dev[6])            # [sp, W] word -> group
    for s, t in enumerate(shards):
        w = int(t.group_words.sum())
        pad_groups = np.unique(grp[s, w:])
        assert topo[s, pad_groups].sum() == 0, s
        assert dc[s, pad_groups].sum() == 0, s


def test_sharded_sig_scale_100k_and_reshard():
    """Scale-up cluster parity (VERDICT r1 #7): >=100K filters with
    mixed $share/'#'/deep shapes over 8 shards must match the trie
    exactly — the cross-shard invariants (shared intern pool, union
    exact groups, shard-0 tokenization serving all shards) only break
    at scale. Then simulate losing half the mesh: reshard to 4 devices
    and assert exact parity again (elastic recovery by recompile)."""
    rng = random.Random(77)
    alphabet = [f"{c}{i}" for c in "abcdefgh" for i in range(12)]
    filters = []
    for _ in range(100_000):
        depth = rng.randint(1, 8)
        levels = [rng.choice(alphabet) for _ in range(depth)]
        r = rng.random()
        if r < 0.3:
            levels[rng.randrange(depth)] = "+"
        elif r < 0.45:
            levels = levels[: rng.randint(1, depth)] + ["#"]
        f = "/".join(levels)
        if rng.random() < 0.1:
            f = f"$share/g{rng.randint(0, 4)}/{f}"
        filters.append(f)
    index = build_index(filters)
    topics = ["/".join(rng.choice(alphabet)
                       for _ in range(rng.randint(1, 8)))
              for _ in range(256)]
    topics += ["$SYS/broker/load", "a0//b0", "/a0"]

    engine = ShardedSigEngine(index, mesh=make_mesh(shape=(1, 8)))
    got = engine.subscribers_batch(topics)
    n_matched = 0
    for topic, g in zip(topics, got):
        want = index.subscribers(topic)
        assert_same(g, want, topic)
        n_matched += len(want.subscriptions) + len(want.shared)
    assert n_matched > 500, "corpus too sparse to be a meaningful test"

    # half the devices "fail": recompile over a (1, 4) mesh
    engine.reshard(make_mesh(shape=(1, 4)))
    assert engine.sp == 4
    got = engine.subscribers_batch(topics[:64])
    for topic, g in zip(topics[:64], got):
        assert_same(g, index.subscribers(topic), topic)


def test_sharded_sig_multislice_mesh_parity():
    """DCN/multi-slice story: subscriptions partition over
    ('slice', 'subs') jointly; the match program never communicates
    across 'slice', so only host result gathers cross the (slow)
    inter-slice fabric. Virtual 2-slice x (data 1|2 x subs 2) meshes
    must match the trie exactly."""
    from maxmq_tpu.parallel.sharded import make_multislice_mesh

    filters, topics = random_corpus(400, 48, seed=21)
    index = build_index(filters)
    for shape in [(1, 2), (2, 2)]:
        mesh = make_multislice_mesh(n_slices=2, shape=shape)
        assert mesh.axis_names == ("slice", "data", "subs")
        engine = ShardedSigEngine(index, mesh=mesh)
        assert engine.sp == 2 * shape[1]
        got = engine.subscribers_batch(topics)
        for topic, g in zip(topics, got):
            assert_same(g, index.subscribers(topic), topic)

    # elastic: drop to a single-slice 2-axis mesh and back
    engine.reshard(make_mesh(shape=(1, 4)))
    got = engine.subscribers_batch(topics[:16])
    for topic, g in zip(topics[:16], got):
        assert_same(g, index.subscribers(topic), topic)


def test_sharded_sig_uneven_and_empty_shards():
    # fewer filters than shards: some shards compile empty
    index = build_index(["alpha/beta", "alpha/+", "gamma/#"])
    engine = ShardedSigEngine(index, mesh=make_mesh(shape=(1, 8)))
    for topic in ["alpha/beta", "gamma/x/y", "delta", "alpha"]:
        assert_same(engine.subscribers(topic), index.subscribers(topic),
                    topic)


async def test_cluster_broker_qos12_offline_redelivery():
    """BASELINE config 5 end-to-end (VERDICT r03 #6): a real broker with
    the ShardedSigEngine attached drives QoS1 and QoS2 flows — live
    delivery, exactly-once dedup, and persistent-session offline
    redelivery — with every match answered by the sharded matcher on
    the 8-device CPU mesh."""
    import asyncio

    from test_broker_system import connect, running_broker

    from maxmq_tpu.matching.batcher import MicroBatcher
    from maxmq_tpu.mqtt_client import MQTTClient
    from maxmq_tpu.parallel.sharded import ShardedSigEngine

    async with running_broker() as broker:
        eng = ShardedSigEngine(broker.topics, mesh=make_mesh())
        mb = MicroBatcher(eng, window_us=0, cpu_bypass=False)
        broker.attach_matcher(mb)
        s = await connect(broker, "cs-sub", clean_start=False)
        await s.subscribe(("cs/q/#", 1), ("cs/e/t", 2))
        p = await connect(broker, "cs-pub")

        # QoS1 live delivery through the sharded matcher
        await p.publish("cs/q/a", b"live", qos=1)
        m = await s.next_message(timeout=60)
        assert (m.topic, m.payload, m.qos) == ("cs/q/a", b"live", 1)

        # QoS2 exactly-once through the sharded matcher
        for i in range(3):
            await p.publish("cs/e/t", f"m{i}".encode(), qos=2)
        got = [await s.next_message(timeout=60) for _ in range(3)]
        assert [g.payload for g in got] == [b"m0", b"m1", b"m2"]
        assert all(g.qos == 2 for g in got)

        # the sharded engine answered the matches (not a trie fallback).
        # Only DISTINCT topics are guaranteed to reach the engine — the
        # batcher's version-keyed cache may serve repeats (that's its
        # job), so the floor is 2 (cs/q/a, cs/e/t), not one per publish.
        assert eng.matches >= 2
        fallback_frac = eng.fallbacks / max(eng.matches, 1)
        assert fallback_frac < 0.5, (eng.fallbacks, eng.matches)

        # persistent-session offline QoS1 redelivery: the sharded match
        # must still name the disconnected session's client
        await s.close()                    # network drop, not DISCONNECT
        await asyncio.sleep(0.1)
        await p.publish("cs/q/offline", b"queued", qos=1)
        s2 = MQTTClient(client_id="cs-sub", clean_start=False)
        await s2.connect("127.0.0.1", broker.test_port)
        assert s2.connack.session_present is True
        m = await s2.next_message(timeout=60)
        assert (m.payload, m.qos) == (b"queued", 1)
        await s2.disconnect()
        await p.disconnect()
        await mb.close()


def test_sharded_chain_in_chain_parity():
    """Cluster chain composition: per-shard results that are themselves
    CHAINED intents (fat '#' bucket split across client-hash shards)
    iterate correctly inside the cluster-level ChainedIntents — no
    duplicate clients, exact trie parity, n/len/to_set agree."""
    from test_nfa_parity import normalize

    from maxmq_tpu.native import decode_module
    mod = decode_module()
    if mod is None or not hasattr(mod, "_set_chain_params"):
        pytest.skip("maxmq_decode extension unavailable")
    from maxmq_tpu.parallel.sharded import ChainedIntents, ShardedSigEngine

    idx = TopicIndex()
    for i in range(200):
        idx.subscribe(f"fat{i}", Subscription(filter="cc/dev/#", qos=1))
    idx.subscribe("fat3", Subscription(filter="cc/dev/a/b", qos=2,
                                       identifier=5))
    idx.subscribe("solo", Subscription(filter="cc/dev/+/b", qos=1))
    idx.subscribe("sh1", Subscription(filter="$share/g/cc/dev/#", qos=1))
    # client-hash sharding splits the 200 fat clients ~25 per shard —
    # drop the chain threshold so every shard's fat row anchors a chain
    from maxmq_tpu.native import chain_params_in_effect
    saved = chain_params_in_effect(mod)
    mod._set_chain_params(8, 4, 1)
    try:
        eng = ShardedSigEngine(idx, mesh=make_mesh())
        eng.emit_intents = True
        topics = ["cc/dev/a/b", "cc/dev/x/b", "cc/dev/z", "no/match"]
        got = eng.subscribers_batch(topics)
        saw_nested = 0
        for topic, r in zip(topics, got):
            want = idx.subscribers(topic)
            if not isinstance(r, ChainedIntents):
                assert normalize(getattr(r, "to_set", lambda: r)()) \
                    == normalize(want), topic
                continue
            saw_nested += sum(
                1 for p in r.parts if getattr(p, "chained", False))
            by_iter = {}
            for cid, sub in r:
                assert cid not in by_iter, (topic, cid)
                by_iter[cid] = sub
            assert len(by_iter) == r.n, topic
            assert set(by_iter) == set(want.subscriptions), topic
            for cid, sub in by_iter.items():
                w = want.subscriptions[cid]
                assert (sub.qos, dict(sub.identifiers)) == \
                    (w.qos, dict(w.identifiers)), (topic, cid)
            assert normalize(r.to_set()) == normalize(want), topic
        assert saw_nested, "no per-shard chained intents engaged"
    finally:
        mod._set_chain_params(*saved)


@pytest.mark.parametrize("seed", [21, 22])
def test_sharded_intents_parity(seed):
    """Cluster-mode ADR 007: chained per-shard DeliveryIntents must
    match the CPU trie exactly (client-hash sharding makes the chain
    merge-free), including $share groups spanning shards and the
    to_set()/has_client surface."""
    from test_nfa_parity import normalize

    from maxmq_tpu.native import decode_module
    if decode_module() is None:
        pytest.skip("maxmq_decode extension unavailable")
    from maxmq_tpu.parallel.sharded import ChainedIntents, ShardedSigEngine

    filters, topics = random_corpus(250, 120, seed)
    idx = TopicIndex()
    from maxmq_tpu.matching.topics import valid_filter
    rng = random.Random(seed)
    for i, f in enumerate(filters):
        if not valid_filter(f):
            continue
        idx.subscribe(f"cl{i % 60}",
                      Subscription(filter=f, qos=rng.randint(0, 2),
                                   identifier=rng.randint(0, 3)))
    eng = ShardedSigEngine(idx, mesh=make_mesh())
    eng.emit_intents = True
    got = eng.subscribers_batch(topics)
    saw_chained = 0
    for topic, r in zip(topics, got):
        want = idx.subscribers(topic)
        if isinstance(r, ChainedIntents):
            saw_chained += 1
            by_iter = {cid: sub for cid, sub in r}
            assert len(by_iter) == r.n, f"client chained twice: {topic}"
            assert set(by_iter) == set(want.subscriptions), topic
            for cid in by_iter:
                assert r.has_client(cid)
            s = r.to_set()
            assert normalize(s) == normalize(want), topic
        else:
            to_set = getattr(r, "to_set", None)
            s = to_set() if to_set is not None else r
            assert normalize(s) == normalize(want), topic
    assert saw_chained, "chained intents path never engaged"


async def test_sharded_intents_broker_delivery():
    """The broker consumes ChainedIntents end-to-end (QoS1 + $share)."""
    from test_broker_system import connect, running_broker

    from maxmq_tpu.matching.batcher import MicroBatcher
    from maxmq_tpu.parallel.sharded import ShardedSigEngine

    async with running_broker() as broker:
        eng = ShardedSigEngine(broker.topics, mesh=make_mesh())
        eng.emit_intents = True
        mb = MicroBatcher(eng, window_us=0, cpu_bypass=False)
        broker.attach_matcher(mb)
        s = await connect(broker, "ci-sub", version=5)
        await s.subscribe(("ci/+/x", 1))
        g1 = await connect(broker, "ci-g1")
        await g1.subscribe(("$share/g/ci/sh", 0))
        p = await connect(broker, "ci-pub")
        await p.publish("ci/a/x", b"one", qos=1)
        m = await s.next_message(timeout=60)
        assert (m.topic, m.payload, m.qos) == ("ci/a/x", b"one", 1)
        await p.publish("ci/sh", b"sh")
        m = await g1.next_message(timeout=60)
        assert m.payload == b"sh"
        for c in (s, g1, p):
            await c.disconnect()
        await mb.close()




def test_heavy_client_falls_back_to_round_robin(monkeypatch):
    """One client whose wildcard shapes overflow a client-hash bucket's
    MAX_GROUPS must not disable device matching: refresh re-partitions
    round-robin (spreading the shapes) and turns chaining off, with
    exact results either way."""
    import maxmq_tpu.matching.sig as sigmod
    from test_nfa_parity import normalize

    from maxmq_tpu.parallel.sharded import ChainedIntents, ShardedSigEngine

    monkeypatch.setattr(sigmod, "MAX_GROUPS", 4)
    idx = TopicIndex()
    # a bridge client with 8 distinct '#'-shapes (device groups; depth
    # varies — trailing-'+' shapes would be host-probed, not grouped)
    for d in range(2, 10):
        idx.subscribe("bridge", Subscription(
            filter="/".join(["alpha"] * d) + "/#", qos=1))
    idx.subscribe("plain", Subscription(filter="alpha/beta", qos=0))
    eng = ShardedSigEngine(idx, mesh=make_mesh(shape=(1, 8)))
    eng.emit_intents = True
    assert eng._state[3] is not None, "device path must stay alive"
    assert eng._state[7] is False, "chaining must be off under round-robin"
    topics = ["alpha/beta", "alpha/alpha/x", "alpha/alpha/alpha/y"]
    got = eng.subscribers_batch(topics)
    for t, r in zip(topics, got):
        assert not isinstance(r, ChainedIntents)
        assert normalize(r) == normalize(idx.subscribers(t)), t


def test_client_hash_empty_buckets_ok():
    """Client-hash partitioning with fewer clients than shards leaves
    empty buckets — matching and chaining must work regardless."""
    from maxmq_tpu.native import decode_module
    if decode_module() is None:
        pytest.skip("maxmq_decode extension unavailable")
    from maxmq_tpu.parallel.sharded import ShardedSigEngine

    idx = TopicIndex()
    idx.subscribe("only-a", Subscription(filter="eb/+/t", qos=1))
    idx.subscribe("only-b", Subscription(filter="eb/#", qos=0))
    eng = ShardedSigEngine(idx, mesh=make_mesh(shape=(1, 8)))
    eng.emit_intents = True
    got = eng.subscribers_batch(["eb/x/t", "eb/y", "zz"])
    s0 = got[0].to_set() if hasattr(got[0], "to_set") else got[0]
    assert set(s0.subscriptions) == {"only-a", "only-b"}
    s1 = got[1].to_set() if hasattr(got[1], "to_set") else got[1]
    assert set(s1.subscriptions) == {"only-b"}
    assert len(got[2]) == 0


@pytest.mark.parametrize("seed", [21, 22])
def test_sharded_host_batch_parity(seed):
    """Cluster-mode device-free path (subscribers_host_batch: per-shard
    exact/'+'/'#' host probes + chained native decode, no mesh
    dispatch) matches the CPU trie exactly in both result forms."""
    from test_nfa_parity import normalize

    from maxmq_tpu.parallel.sharded import ShardedSigEngine

    filters, topics = random_corpus(250, 120, seed)
    idx = TopicIndex()
    from maxmq_tpu.matching.topics import valid_filter
    rng = random.Random(seed)
    for i, f in enumerate(filters):
        if not valid_filter(f):
            continue
        idx.subscribe(f"cl{i % 60}",
                      Subscription(filter=f, qos=rng.randint(0, 2),
                                   identifier=rng.randint(0, 3)))
    eng = ShardedSigEngine(idx, mesh=make_mesh())
    for emit in (False, True):
        eng.emit_intents = emit
        got = eng.subscribers_host_batch(topics)
        for topic, r in zip(topics, got):
            want = idx.subscribers(topic)
            to_set = getattr(r, "to_set", None)
            s = to_set() if to_set is not None else r
            assert normalize(s) == normalize(want), (topic, emit)
    assert eng.host_matches == 2 * len(topics)


def test_sharded_host_batch_overflow_topic_falls_back():
    """Regression: prepare_batch_sig reports too-deep topics as
    lengths == -1 (not >= 127) — the host path must still serve them
    from the trie, exactly like the device path's 0xF marker."""
    idx = TopicIndex()
    idx.subscribe("deepwatch", Subscription(filter="#", qos=1))
    idx.subscribe("plain", Subscription(filter="alpha/beta", qos=0))
    eng = ShardedSigEngine(idx, mesh=make_mesh())
    deep = "/".join(["alpha"] * 80)          # > DEPTH_CAP
    for emit in (False, True):
        eng.emit_intents = emit
        before = eng.host_matches
        got = eng.subscribers_host_batch([deep, "alpha/beta"])
        to_set = getattr(got[0], "to_set", None)
        s0 = to_set() if to_set is not None else got[0]
        assert "deepwatch" in s0.subscriptions, "overflow topic lost"
        # the overflow topic was trie-served, not a host match
        assert eng.host_matches == before + 1
