"""Storage hook + stores (hooks/storage.py): record round-trips, both
backends, the write-through event surface, and full broker restore.

Parity surface: the reference's hooks/storage types + Stored* plumbing
(vendor/.../v2/hooks/storage/storage.go:29-193, server.go:1297-1434);
it vendors no backend — this repo's Memory/SQLite stores exceed it."""

import asyncio

from test_broker_system import connect, running_broker

from maxmq_tpu.broker import Broker, BrokerOptions, Capabilities, TCPListener
from maxmq_tpu.hooks import AllowHook
from maxmq_tpu.hooks.storage import (ClientRecord, MemoryStore,
                                     MessageRecord, SQLiteStore,
                                     StorageHook, SubscriptionRecord)
from maxmq_tpu.mqtt_client import MQTTClient
from maxmq_tpu.protocol.codec import FixedHeader, PacketType as PT
from maxmq_tpu.protocol.packets import Packet, Properties


def test_record_json_round_trips():
    c = ClientRecord(client_id="c1", listener="tcp", username=b"u\xff",
                     clean=True, protocol_version=5, session_expiry=30,
                     session_expiry_set=True, disconnected_at=12.5)
    c2 = ClientRecord.from_json(c.to_json())
    assert (c2.client_id, c2.protocol_version, c2.session_expiry,
            c2.session_expiry_set) == ("c1", 5, 30, True)

    s = SubscriptionRecord(client_id="c1", filter="a/+", qos=2,
                           no_local=True, retain_as_published=True,
                           retain_handling=2, identifier=7)
    assert SubscriptionRecord.from_json(s.to_json()) == s

    m = MessageRecord(client_id="c1", topic="t/x", payload=b"\x00\xffp",
                      qos=1, retain=True, packet_id=9, created=1.0)
    m2 = MessageRecord.from_json(m.to_json())
    assert m2.payload == b"\x00\xffp" and m2.packet_id == 9


def test_message_record_packet_round_trip_v5_properties():
    p = Packet(fixed=FixedHeader(type=PT.PUBLISH, qos=1, retain=True),
               topic="t/v5", payload=b"body", packet_id=3,
               origin="orig", created=2.0,
               properties=Properties(
                   payload_format=1, message_expiry=60,
                   content_type="text/plain", response_topic="r/t",
                   correlation_data=b"\x01\x02",
                   user_properties=[("k", "v")]))
    rec = MessageRecord.from_packet(p, client_id="c9")
    back = MessageRecord.from_json(rec.to_json()).to_packet()
    assert back.topic == "t/v5" and back.payload == b"body"
    assert back.fixed.qos == 1 and back.fixed.retain
    assert back.properties.content_type == "text/plain"
    assert back.properties.correlation_data == b"\x01\x02"
    assert back.properties.user_properties == [("k", "v")]
    assert back.properties.message_expiry == 60


def test_sqlite_store_operations_and_persistence(tmp_path):
    path = str(tmp_path / "s.db")
    st = SQLiteStore(path)
    st.put("b1", "k1", "v1")
    st.put("b1", "k2", "v2")
    st.put("b2", "k1", "other")
    assert st.get("b1", "k1") == "v1"
    assert st.get("b1", "missing") is None
    assert st.all("b1") == {"k1": "v1", "k2": "v2"}
    st.delete("b1", "k1")
    assert st.get("b1", "k1") is None
    st.put("b1", "pre:a", "1")
    st.put("b1", "pre:b", "2")
    st.delete_prefix("b1", "pre:")
    assert st.all("b1") == {"k2": "v2"}
    st.close()
    st2 = SQLiteStore(path)            # survives reopen
    assert st2.get("b2", "k1") == "other"
    st2.close()


def test_memory_store_prefix_delete():
    st = MemoryStore()
    st.put("b", "x:1", "a")
    st.put("b", "x:2", "b")
    st.put("b", "y:1", "c")
    st.delete_prefix("b", "x:")
    assert st.all("b") == {"y:1": "c"}


async def test_write_through_events_and_expiry_cleanup():
    """The hook's event surface against MemoryStore: session, subs,
    retained, inflight write-through; expiry deletes everything."""
    store = MemoryStore()
    async with running_broker() as broker:
        broker.add_hook(StorageHook(store))
        c = await connect(broker, "st-c1", version=4, clean_start=False)
        await c.subscribe(("st/+", 1))
        assert store.all("clients")           # session persisted
        assert any("st/+" in v for v in store.all("subscriptions").values())
        p = await connect(broker, "st-pub")
        await p.publish("st/keep", b"r", qos=0, retain=True)
        await asyncio.sleep(0.05)
        assert any("st/keep" in v for v in store.all("retained").values())
        # clear the retained message -> record removed
        await p.publish("st/keep", b"", qos=0, retain=True)
        await asyncio.sleep(0.05)
        assert not any("st/keep" in v
                       for v in store.all("retained").values())
        await c.unsubscribe("st/+")
        await asyncio.sleep(0.05)
        assert not any('"st/+"' in v
                       for v in store.all("subscriptions").values())
        await c.disconnect()
        await p.disconnect()


async def test_full_restore_across_broker_restart(tmp_path):
    """Offline QoS1 redelivery across a full broker restart (the
    reference's readStore path, server.go:1297-1434): persistent
    session + inflight + retained all restore from SQLite."""
    path = str(tmp_path / "restore.db")

    async def start(port_holder):
        b = Broker(BrokerOptions(capabilities=Capabilities(
            sys_topic_interval=0)))
        b.add_hook(AllowHook())
        b.add_hook(StorageHook(SQLiteStore(path)))
        lst = b.add_listener(TCPListener("t", "127.0.0.1:0"))
        await b.serve()
        port_holder.append(lst._server.sockets[0].getsockname()[1])
        return b

    ports: list[int] = []
    b1 = await start(ports)
    sub = MQTTClient(client_id="rs-sub", clean_start=False)
    await sub.connect("127.0.0.1", ports[0])
    await sub.subscribe(("rs/x", 1))
    await sub.disconnect()
    pub = MQTTClient(client_id="rs-pub")
    await pub.connect("127.0.0.1", ports[0])
    await pub.publish("rs/x", b"queued", qos=1)
    await pub.publish("rs/ret", b"kept", qos=0, retain=True)
    await asyncio.sleep(0.1)
    await pub.disconnect()
    await b1.close()

    b2 = await start(ports)            # fresh broker, same store
    try:
        sub2 = MQTTClient(client_id="rs-sub", clean_start=False)
        await sub2.connect("127.0.0.1", ports[1])
        assert sub2.connack.session_present is True
        m = await sub2.next_message(timeout=10)
        assert m.payload == b"queued"  # offline inflight redelivered
        fresh = MQTTClient(client_id="rs-fresh")
        await fresh.connect("127.0.0.1", ports[1])
        await fresh.subscribe(("rs/ret", 0))
        m = await fresh.next_message(timeout=10)
        assert m.payload == b"kept" and m.retain
        await sub2.disconnect()
        await fresh.disconnect()
    finally:
        await b2.close()
