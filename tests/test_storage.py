"""Storage hook + stores (hooks/storage.py): record round-trips, both
backends, the write-through event surface, and full broker restore —
plus the ADR-014 crash-consistent pipeline: write-behind journal
(group commit, coalescing, durability barriers), storage degradation
breaker, per-record quarantine, SQLite integrity move-aside, and the
persisted boot epoch.

Parity surface: the reference's hooks/storage types + Stored* plumbing
(vendor/.../v2/hooks/storage/storage.go:29-193, server.go:1297-1434);
it vendors no backend — this repo's Memory/SQLite stores exceed it."""

import asyncio
import json
import threading
import time

import pytest
from test_broker_system import connect, running_broker

from maxmq_tpu import faults
from maxmq_tpu.broker import Broker, BrokerOptions, Capabilities, TCPListener
from maxmq_tpu.broker.inflight import Inflight
from maxmq_tpu.hooks import AllowHook
from maxmq_tpu.hooks.journal import (BREAKER_CLOSED, BREAKER_OPEN,
                                     WriteBehindStore)
from maxmq_tpu.hooks.storage import (ClientRecord, MemoryStore,
                                     MessageRecord, SQLiteStore,
                                     StorageHook, SubscriptionRecord)
from maxmq_tpu.mqtt_client import MQTTClient
from maxmq_tpu.protocol.codec import FixedHeader, PacketType as PT
from maxmq_tpu.protocol.packets import Packet, Properties


class GatedStore(MemoryStore):
    """MemoryStore whose apply_batch blocks on an event and/or raises on
    command — deterministic control over the journal writer thread."""

    def __init__(self):
        super().__init__()
        self.gate = threading.Event()
        self.gate.set()
        self.fail = False
        self.batches = 0

    def apply_batch(self, ops):
        self.gate.wait(timeout=10.0)
        if self.fail:
            raise OSError("injected backend failure")
        self.batches += 1
        super().apply_batch(ops)


def test_record_json_round_trips():
    c = ClientRecord(client_id="c1", listener="tcp", username=b"u\xff",
                     clean=True, protocol_version=5, session_expiry=30,
                     session_expiry_set=True, disconnected_at=12.5)
    c2 = ClientRecord.from_json(c.to_json())
    assert (c2.client_id, c2.protocol_version, c2.session_expiry,
            c2.session_expiry_set) == ("c1", 5, 30, True)

    s = SubscriptionRecord(client_id="c1", filter="a/+", qos=2,
                           no_local=True, retain_as_published=True,
                           retain_handling=2, identifier=7)
    assert SubscriptionRecord.from_json(s.to_json()) == s

    m = MessageRecord(client_id="c1", topic="t/x", payload=b"\x00\xffp",
                      qos=1, retain=True, packet_id=9, created=1.0)
    m2 = MessageRecord.from_json(m.to_json())
    assert m2.payload == b"\x00\xffp" and m2.packet_id == 9


def test_message_record_packet_round_trip_v5_properties():
    p = Packet(fixed=FixedHeader(type=PT.PUBLISH, qos=1, retain=True),
               topic="t/v5", payload=b"body", packet_id=3,
               origin="orig", created=2.0,
               properties=Properties(
                   payload_format=1, message_expiry=60,
                   content_type="text/plain", response_topic="r/t",
                   correlation_data=b"\x01\x02",
                   user_properties=[("k", "v")]))
    rec = MessageRecord.from_packet(p, client_id="c9")
    back = MessageRecord.from_json(rec.to_json()).to_packet()
    assert back.topic == "t/v5" and back.payload == b"body"
    assert back.fixed.qos == 1 and back.fixed.retain
    assert back.properties.content_type == "text/plain"
    assert back.properties.correlation_data == b"\x01\x02"
    assert back.properties.user_properties == [("k", "v")]
    assert back.properties.message_expiry == 60


def test_sqlite_store_operations_and_persistence(tmp_path):
    path = str(tmp_path / "s.db")
    st = SQLiteStore(path)
    st.put("b1", "k1", "v1")
    st.put("b1", "k2", "v2")
    st.put("b2", "k1", "other")
    assert st.get("b1", "k1") == "v1"
    assert st.get("b1", "missing") is None
    assert st.all("b1") == {"k1": "v1", "k2": "v2"}
    st.delete("b1", "k1")
    assert st.get("b1", "k1") is None
    st.put("b1", "pre:a", "1")
    st.put("b1", "pre:b", "2")
    st.delete_prefix("b1", "pre:")
    assert st.all("b1") == {"k2": "v2"}
    st.close()
    st2 = SQLiteStore(path)            # survives reopen
    assert st2.get("b2", "k1") == "other"
    st2.close()


def test_memory_store_prefix_delete():
    st = MemoryStore()
    st.put("b", "x:1", "a")
    st.put("b", "x:2", "b")
    st.put("b", "y:1", "c")
    st.delete_prefix("b", "x:")
    assert st.all("b") == {"y:1": "c"}


async def test_write_through_events_and_expiry_cleanup():
    """The hook's event surface against MemoryStore: session, subs,
    retained, inflight write-through; expiry deletes everything."""
    store = MemoryStore()
    async with running_broker() as broker:
        broker.add_hook(StorageHook(store))
        c = await connect(broker, "st-c1", version=4, clean_start=False)
        await c.subscribe(("st/+", 1))
        assert store.all("clients")           # session persisted
        assert any("st/+" in v for v in store.all("subscriptions").values())
        p = await connect(broker, "st-pub")
        await p.publish("st/keep", b"r", qos=0, retain=True)
        await asyncio.sleep(0.05)
        assert any("st/keep" in v for v in store.all("retained").values())
        # clear the retained message -> record removed
        await p.publish("st/keep", b"", qos=0, retain=True)
        await asyncio.sleep(0.05)
        assert not any("st/keep" in v
                       for v in store.all("retained").values())
        await c.unsubscribe("st/+")
        await asyncio.sleep(0.05)
        assert not any('"st/+"' in v
                       for v in store.all("subscriptions").values())
        await c.disconnect()
        await p.disconnect()


async def test_full_restore_across_broker_restart(tmp_path):
    """Offline QoS1 redelivery across a full broker restart (the
    reference's readStore path, server.go:1297-1434): persistent
    session + inflight + retained all restore from SQLite."""
    path = str(tmp_path / "restore.db")

    async def start(port_holder):
        b = Broker(BrokerOptions(capabilities=Capabilities(
            sys_topic_interval=0)))
        b.add_hook(AllowHook())
        b.add_hook(StorageHook(SQLiteStore(path)))
        lst = b.add_listener(TCPListener("t", "127.0.0.1:0"))
        await b.serve()
        port_holder.append(lst._server.sockets[0].getsockname()[1])
        return b

    ports: list[int] = []
    b1 = await start(ports)
    sub = MQTTClient(client_id="rs-sub", clean_start=False)
    await sub.connect("127.0.0.1", ports[0])
    await sub.subscribe(("rs/x", 1))
    await sub.disconnect()
    pub = MQTTClient(client_id="rs-pub")
    await pub.connect("127.0.0.1", ports[0])
    await pub.publish("rs/x", b"queued", qos=1)
    await pub.publish("rs/ret", b"kept", qos=0, retain=True)
    await asyncio.sleep(0.1)
    await pub.disconnect()
    await b1.close()

    b2 = await start(ports)            # fresh broker, same store
    try:
        sub2 = MQTTClient(client_id="rs-sub", clean_start=False)
        await sub2.connect("127.0.0.1", ports[1])
        assert sub2.connack.session_present is True
        m = await sub2.next_message(timeout=10)
        assert m.payload == b"queued"  # offline inflight redelivered
        fresh = MQTTClient(client_id="rs-fresh")
        await fresh.connect("127.0.0.1", ports[1])
        await fresh.subscribe(("rs/ret", 0))
        m = await fresh.next_message(timeout=10)
        assert m.payload == b"kept" and m.retain
        await sub2.disconnect()
        await fresh.disconnect()
    finally:
        await b2.close()


# ---------------------------------------------------------------------------
# ADR 014: forward-compat records + quarantine-tolerant restore
# ---------------------------------------------------------------------------


def test_from_json_drops_unknown_keys_all_records():
    """A record written by a NEWER build restores after a downgrade:
    unknown keys drop instead of raising TypeError in cls(**d)."""
    c = json.loads(ClientRecord(client_id="c1").to_json())
    c["future_field"] = {"nested": True}
    assert ClientRecord.from_json(json.dumps(c)).client_id == "c1"

    s = json.loads(SubscriptionRecord(client_id="c1", filter="a/+").to_json())
    s["delivery_priority"] = 9
    assert SubscriptionRecord.from_json(json.dumps(s)).filter == "a/+"

    m = json.loads(MessageRecord(topic="t", payload=b"x").to_json())
    m["compression"] = "zstd"
    back = MessageRecord.from_json(json.dumps(m))
    assert back.topic == "t" and back.payload == b"x"


def test_restore_quarantines_torn_records_instead_of_aborting():
    store = MemoryStore()
    good = SubscriptionRecord(client_id="c1", filter="ok/#").to_json()
    store.put("subscriptions", "c1|ok/#", good)
    store.put("subscriptions", "c1|torn", '{"client_id": "c1", "fil')
    store.put("inflight", "c1|7", "\x00not json at all")
    hook = StorageHook(store)
    subs = hook.stored_subscriptions()
    assert [r.filter for r in subs] == ["ok/#"]
    assert hook.stored_inflight_messages() == []
    assert hook.quarantined == 2
    q = store.all("quarantine")
    assert "subscriptions|c1|torn" in q and "inflight|c1|7" in q
    # the torn originals are gone: the next boot doesn't re-trip
    assert "c1|torn" not in store.all("subscriptions")


def test_restore_fault_site_quarantines_one_record():
    store = MemoryStore()
    for i in range(3):
        store.put("retained", f"t/{i}",
                  MessageRecord(topic=f"t/{i}", payload=b"v").to_json())
    hook = StorageHook(store)
    faults.clear()
    try:
        faults.arm(faults.STORAGE_RESTORE, "raise", count=1)
        msgs = hook.stored_retained_messages()
    finally:
        faults.clear()
    assert len(msgs) == 2 and hook.quarantined == 1
    assert len(store.all("quarantine")) == 1


def test_boot_epoch_monotonic():
    store = MemoryStore()
    hook = StorageHook(store)
    first = hook.bump_boot_epoch()
    assert first >= 1_000_000_000_000      # wall-clock ms seed
    # a second boot off the same store is exactly +1, clock-independent
    assert StorageHook(store).bump_boot_epoch() == first + 1
    assert StorageHook(store).bump_boot_epoch() == first + 2


# ---------------------------------------------------------------------------
# ADR 014: write-behind journal
# ---------------------------------------------------------------------------


def test_journal_overlay_reads_and_group_commit():
    inner = GatedStore()
    inner.gate.clear()                     # hold the writer thread
    st = WriteBehindStore(inner, policy="batched", batch_ms=0)
    try:
        st.put("b", "k1", "v1")
        st.put("b", "k2", "v2")
        st.delete("b", "k2")
        st.put("b", "pre:a", "1")
        st.delete_prefix("b", "pre:")
        st.put("b", "pre:b", "2")          # re-put AFTER the prefix delete
        # reads see the pending journal overlaid on the (empty) backend
        assert st.get("b", "k1") == "v1"
        assert st.get("b", "k2") is None
        assert st.all("b") == {"k1": "v1", "pre:b": "2"}
        assert inner.all("b") == {}        # nothing committed yet
        inner.gate.set()
        assert st.flush(timeout=5.0)
        assert inner.all("b") == {"k1": "v1", "pre:b": "2"}
        assert st.commits >= 1 and st.ops_written >= 5
    finally:
        st.close()


def test_journal_coalesces_same_key_rewrites():
    inner = GatedStore()
    inner.gate.clear()
    st = WriteBehindStore(inner, policy="batched", batch_ms=0,
                          queue_bytes=1 << 20)
    try:
        for i in range(200):
            st.put("retained", "sensor/1", f"value-{i}")
        assert st.queue_depth == 1          # one queued op, latest value
        assert st.coalesced == 199
        assert st.get("retained", "sensor/1") == "value-199"
        inner.gate.set()
        assert st.flush(timeout=5.0)
        assert inner.get("retained", "sensor/1") == "value-199"
    finally:
        st.close()


def test_journal_watermark_overflow_counted():
    inner = GatedStore()
    inner.gate.clear()
    st = WriteBehindStore(inner, policy="batched", batch_ms=0,
                          queue_bytes=256)
    try:
        for i in range(20):
            st.put("b", f"k{i}", "x" * 64)
        assert st.over_watermark and st.overflows > 0
        inner.gate.set()
        assert st.flush(timeout=5.0)
        assert not st.over_watermark        # drained below the budget
    finally:
        st.close()


async def test_journal_durability_barrier_resolves_on_commit():
    inner = GatedStore()
    inner.gate.clear()
    st = WriteBehindStore(inner, policy="always")
    loop = asyncio.get_running_loop()
    try:
        assert st.barrier(loop) is None     # idle journal: no wait
        st.put("b", "k", "v")
        fut = st.barrier(loop)
        assert fut is not None
        await asyncio.sleep(0.05)
        assert not fut.done()               # backend gated: not durable
        inner.gate.set()
        await asyncio.wait_for(fut, timeout=5.0)
        assert inner.get("b", "k") == "v"   # durable BEFORE the barrier
    finally:
        st.close()


async def test_journal_breaker_opens_releases_barriers_and_recovers():
    """The storage degradation ladder end to end: consecutive commit
    failures trip the breaker (memory-backed writes, dirty flag, all
    barriers released), a half-open reprobe after backoff replays the
    parked journal, and the backend converges to every write."""
    inner = GatedStore()
    inner.fail = True
    st = WriteBehindStore(inner, policy="always", breaker_threshold=3,
                          backoff_s=0.05, backoff_max_s=0.2)
    loop = asyncio.get_running_loop()
    try:
        st.put("b", "k1", "v1")
        fut = st.barrier(loop)
        deadline = time.monotonic() + 5.0
        while st.breaker_state != BREAKER_OPEN:
            assert time.monotonic() < deadline, "breaker never opened"
            await asyncio.sleep(0.01)
        assert st.breaker_trips >= 1 and st.dirty
        # the pending barrier was released degraded, counted as such
        await asyncio.wait_for(fut, timeout=2.0)
        assert st.barriers_released_degraded >= 1
        # degraded mode: writes still land (parked journal), reads see
        # them, and new barriers don't wait
        st.put("b", "k2", "v2")
        assert st.get("b", "k2") == "v2"
        assert st.barrier(loop) is None
        assert st.commit_failures >= 3
        # heal the backend: the half-open reprobe replays everything
        inner.fail = False
        deadline = time.monotonic() + 5.0
        while st.breaker_state != BREAKER_CLOSED:
            assert time.monotonic() < deadline, "breaker never recovered"
            await asyncio.sleep(0.01)
        assert st.flush(timeout=5.0)
        assert inner.all("b") == {"k1": "v1", "k2": "v2"}
        assert st.breaker_recoveries == 1
        assert st.degraded_seconds > 0
    finally:
        st.close()


def test_journal_put_fault_site_counts_and_drops():
    inner = GatedStore()
    st = WriteBehindStore(inner, policy="batched", batch_ms=0)
    faults.clear()
    try:
        faults.arm(faults.STORAGE_PUT, "raise", count=1)
        st.put("b", "lost", "v")
        st.put("b", "kept", "v")
        assert st.put_failures == 1 and st.dirty
        assert st.flush(timeout=5.0)
        assert inner.all("b") == {"kept": "v"}
    finally:
        faults.clear()
        st.close()


def test_journal_commit_fault_site_parks_then_replays():
    inner = GatedStore()
    st = WriteBehindStore(inner, policy="batched", batch_ms=0,
                          breaker_threshold=10)
    faults.clear()
    try:
        faults.arm(faults.STORAGE_COMMIT, "raise", count=2)
        st.put("b", "k", "v")
        assert st.flush(timeout=5.0)        # retried past the 2 failures
        assert inner.get("b", "k") == "v"
        assert st.commit_failures == 2 and st.dirty
        assert st.breaker_state == BREAKER_CLOSED
    finally:
        faults.clear()
        st.close()


def test_journal_rejects_unknown_policy():
    with pytest.raises(ValueError):
        WriteBehindStore(MemoryStore(), policy="sometimes")


# ---------------------------------------------------------------------------
# ADR 014: SQLite hardening
# ---------------------------------------------------------------------------


def test_sqlite_synchronous_pragma_follows_policy(tmp_path):
    from maxmq_tpu.hooks.journal import SQLITE_SYNC_BY_POLICY
    for policy, expect in (("always", 2), ("batched", 2), ("off", 0)):
        st = SQLiteStore(str(tmp_path / f"{policy}.db"),
                         synchronous=SQLITE_SYNC_BY_POLICY[policy])
        level = st._conn.execute("PRAGMA synchronous").fetchone()[0]
        busy = st._conn.execute("PRAGMA busy_timeout").fetchone()[0]
        st.close()
        assert level == expect and busy == 5000


def test_sqlite_corrupt_file_moved_aside_and_recreated(tmp_path):
    path = str(tmp_path / "bad.db")
    with open(path, "wb") as f:                 # not a SQLite file
        f.write(b"SQLite format 3\x00" + b"\xde\xad\xbe\xef" * 512)
    st = SQLiteStore(path)
    try:
        assert st.corruptions == 1
        assert (tmp_path / "bad.db.corrupt-1").exists()
        st.put("b", "k", "v")                   # fresh file serves writes
        assert st.get("b", "k") == "v"
    finally:
        st.close()
    # a second corruption on the same path picks the next aside slot
    with open(path, "wb") as f:
        f.write(b"garbage" * 100)
    st2 = SQLiteStore(path)
    try:
        assert st2.corruptions == 1
        assert (tmp_path / "bad.db.corrupt-2").exists()
    finally:
        st2.close()


def test_sqlite_apply_batch_single_transaction(tmp_path):
    st = SQLiteStore(str(tmp_path / "batch.db"))
    try:
        st.apply_batch([("put", "b", "k1", "v1"),
                        ("put", "b", "pre:x", "1"),
                        ("delete_prefix", "b", "pre:", None),
                        ("put", "b", "k2", "v2"),
                        ("delete", "b", "k1", None)])
        assert st.all("b") == {"k2": "v2"}
    finally:
        st.close()


# ---------------------------------------------------------------------------
# ADR 014: shed policy + redundant-rewrite elision
# ---------------------------------------------------------------------------


class _StubOverload:
    def __init__(self, shedding):
        self.shedding = shedding


class _StubServer:
    def __init__(self, shedding):
        self.overload = _StubOverload(shedding)


class _StubClient:
    def __init__(self, cid="c1", shedding=False):
        self.id = cid
        self.server = _StubServer(shedding)
        self.inflight = Inflight()


def _retain_packet(topic="shed/t", qos=0):
    return Packet(fixed=FixedHeader(type=PT.PUBLISH, qos=qos, retain=True),
                  topic=topic, payload=b"v", created=1.0)


def test_hook_sheds_qos0_retained_rewrites_past_watermark():
    inner = GatedStore()
    inner.gate.clear()                      # wedge the backend
    st = WriteBehindStore(inner, policy="batched", batch_ms=0,
                          queue_bytes=128)
    hook = StorageHook(st)
    try:
        healthy0 = _StubClient(shedding=False)
        for i in range(10):                 # drive past the watermark
            hook.on_retain_message(healthy0, _retain_packet(f"t/{i}"), 1)
        assert st.over_watermark and hook.journal_sheds == 0
        shedding = _StubClient(shedding=True)
        before = st.queue_depth
        hook.on_retain_message(shedding, _retain_packet("t/more"), 1)
        assert hook.journal_sheds == 1 and st.queue_depth == before
        # QoS1 retained writes are never shed — durability-relevant
        hook.on_retain_message(shedding, _retain_packet("t/q1", qos=1), 1)
        assert st.queue_depth == before + 1
        # not shedding (ADR-012 ladder healthy): writes proceed even
        # past the watermark, only counted as overflow
        healthy = _StubClient(shedding=False)
        hook.on_retain_message(healthy, _retain_packet("t/h"), 1)
        assert st.queue_depth == before + 2 and hook.journal_sheds == 1
    finally:
        inner.gate.set()
        st.close()


def test_hook_skips_redundant_inflight_resend_rewrites():
    store = MemoryStore()
    hook = StorageHook(store)
    client = _StubClient("sub1")
    p = Packet(fixed=FixedHeader(type=PT.PUBLISH, qos=1), topic="a/b",
               payload=b"m", packet_id=5, created=1.0)
    client.inflight.set(p)
    hook.on_qos_publish(client, p, 1.0, 0)
    assert len(store.all("inflight")) == 1
    assert client.inflight.stored(5)
    # resend of the already-persisted record: elided
    hook.on_qos_publish(client, p, 2.0, 1)
    assert hook.rewrites_skipped == 1
    # a RESEND of a record the store never saw still writes
    q = Packet(fixed=FixedHeader(type=PT.PUBLISH, qos=1), topic="a/c",
               payload=b"m2", packet_id=6, created=1.0)
    client.inflight.set(q)
    hook.on_qos_publish(client, q, 2.0, 1)
    assert len(store.all("inflight")) == 2
    assert hook.rewrites_skipped == 1
    # ack clears the marker with the entry
    client.inflight.delete(5)
    assert not client.inflight.stored(5)


# ---------------------------------------------------------------------------
# ADR 014: full broker restore THROUGH the journal
# ---------------------------------------------------------------------------


async def test_full_restore_through_write_behind_journal(tmp_path):
    """The PR-ADR-014 pipeline end to end in-process: broker writes ride
    the journal (policy=always → acks barriered), close() flushes, and
    a second broker restores sessions/subs/retained/inflight from the
    same SQLite file while boot_epoch strictly increases."""
    path = str(tmp_path / "journal.db")
    epochs = []

    def build():
        b = Broker(BrokerOptions(capabilities=Capabilities(
            sys_topic_interval=0)))
        b.add_hook(AllowHook())
        b.add_hook(StorageHook(WriteBehindStore(
            SQLiteStore(path), policy="always")))
        b.add_listener(TCPListener("t", "127.0.0.1:0"))
        return b

    b1 = build()
    await b1.serve()
    epochs.append(b1.boot_epoch)
    port = b1.listeners.get("t")._server.sockets[0].getsockname()[1]
    sub = MQTTClient(client_id="wj-sub", clean_start=False)
    await sub.connect("127.0.0.1", port)
    await sub.subscribe(("wj/x", 1))
    await sub.disconnect()
    pub = MQTTClient(client_id="wj-pub")
    await pub.connect("127.0.0.1", port)
    await pub.publish("wj/x", b"queued", qos=1)     # barriered PUBACK
    await pub.publish("wj/ret", b"kept", qos=1, retain=True)
    await pub.disconnect()
    assert b1.storage_barrier_waits > 0             # barrier actually used
    await b1.close()

    b2 = build()
    await b2.serve()
    epochs.append(b2.boot_epoch)
    port = b2.listeners.get("t")._server.sockets[0].getsockname()[1]
    try:
        sub2 = MQTTClient(client_id="wj-sub", clean_start=False)
        await sub2.connect("127.0.0.1", port)
        assert sub2.connack.session_present is True
        m = await sub2.next_message(timeout=10)
        assert m.payload == b"queued"
        fresh = MQTTClient(client_id="wj-fresh")
        await fresh.connect("127.0.0.1", port)
        await fresh.subscribe(("wj/ret", 0))
        m = await fresh.next_message(timeout=10)
        assert m.payload == b"kept" and m.retain
        await sub2.disconnect()
        await fresh.disconnect()
    finally:
        await b2.close()
    assert epochs[1] > epochs[0]


def test_journal_close_with_dead_backend_exits_fast_and_loudly():
    """close() against a backend that never recovers: one final reprobe,
    then the writer exits — parked ops are reported lost (dirty), and
    the thread never spins past the join deadline."""
    inner = GatedStore()
    inner.fail = True
    st = WriteBehindStore(inner, policy="batched", batch_ms=0,
                          breaker_threshold=1, backoff_s=30.0)
    st.put("b", "k", "v")
    deadline = time.monotonic() + 5.0
    while st.breaker_state != BREAKER_OPEN:
        assert time.monotonic() < deadline, "breaker never opened"
        time.sleep(0.01)
    t0 = time.monotonic()
    st.close()
    assert time.monotonic() - t0 < 9.0      # no 30s-backoff wait
    assert st.dirty and not st._thread.is_alive()
