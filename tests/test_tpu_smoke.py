"""Real-TPU smoke test: Pallas kernels active + exact parity on-chip.

The rest of the suite pins the CPU backend process-wide
(tests/conftest.py), so this runs in a SUBPROCESS with the pin stripped.
Skips cleanly when no TPU is reachable (CPU-only boxes, or the tunnel
is down). VERDICT r1 #8: nothing previously asserted ``pallas_active``
on the hardware path.
"""

import os
import subprocess
import sys

import pytest

_SCRIPT = r"""
import json, random, sys
sys.path.insert(0, __REPO__)
import jax
if jax.default_backend() not in ("tpu", "axon"):
    print("SKIP-NO-TPU", jax.default_backend())
    sys.exit(0)

from maxmq_tpu.matching.trie import TopicIndex
from maxmq_tpu.matching.sig import SigEngine
from maxmq_tpu.protocol.packets import Subscription

rng = random.Random(11)
alphabet = [f"t{i}" for i in range(40)]
idx = TopicIndex()
for i in range(4000):
    depth = rng.randint(1, 6)
    levels = [rng.choice(alphabet) for _ in range(depth)]
    r = rng.random()
    if r < 0.3:
        levels[rng.randrange(depth)] = "+"
    elif r < 0.45:
        levels = levels[: rng.randint(1, depth)] + ["#"]
    f = "/".join(levels)
    if rng.random() < 0.1:
        f = f"$share/g{rng.randint(0,2)}/{f}"
    idx.subscribe(f"c{i}", Subscription(filter=f, qos=i % 3))

engine = SigEngine(idx, auto_refresh=False)
engine.route_small = False    # the smoke must hit the device
assert engine.pallas_active, "Pallas kernel must be active on TPU"
topics = ["/".join(rng.choice(alphabet) for _ in range(rng.randint(1, 6)))
          for _ in range(512)] + ["$SYS/broker/x", "a//b"]
got = engine.subscribers_fixed_batch(topics)
checked = 0
for t, s in zip(topics, got):
    want = idx.subscribers(t)
    assert set(s.subscriptions) == set(want.subscriptions), t
    assert set(s.shared) == set(want.shared), t
    checked += len(want.subscriptions)
print("PASS", json.dumps({"topics": len(topics), "matched": checked,
                          "pallas": engine.pallas_active,
                          "backend": jax.default_backend()}))
"""


@pytest.mark.skipif(os.environ.get("MAXMQ_TPU_SMOKE") == "0",
                    reason="disabled via MAXMQ_TPU_SMOKE=0")
def test_tpu_pallas_parity_smoke():
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS",)}
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    timeout = int(os.environ.get("MAXMQ_TPU_SMOKE_TIMEOUT", "240"))
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             _SCRIPT.replace("__REPO__", repr(repo))],
            capture_output=True, text=True, timeout=timeout, env=env)
    except subprocess.TimeoutExpired:
        pytest.skip("TPU unreachable (timeout — tunnel down?)")
    out = proc.stdout
    if "SKIP-NO-TPU" in out:
        pytest.skip(f"no TPU backend: {out.strip()}")
    assert proc.returncode == 0, (
        f"TPU smoke failed rc={proc.returncode}:\n{proc.stderr[-2000:]}")
    assert "PASS" in out, out
