"""ADR-011 fault suite: every rung of the matcher degradation ladder
under deterministic injected faults (maxmq_tpu/faults.py).

For each fault class the ISSUE names — device exception, device hang
past the deadline, recompile failure, matcher-service socket drop,
pool-worker death — an end-to-end match/publish still completes with
results bit-equal to the CPU trie, the breaker trips after the
configured threshold, and a half-open reprobe restores the device path
once the fault clears; all of it observable through the new metrics."""

import asyncio
import io
import time

import pytest

from test_broker_system import connect, running_broker
from test_nfa_parity import normalize

from maxmq_tpu import faults
from maxmq_tpu.matching.batcher import MicroBatcher
from maxmq_tpu.matching.sig import SigEngine
from maxmq_tpu.matching.supervisor import (BREAKER_CLOSED, BREAKER_OPEN,
                                           SupervisedMatcher)
from maxmq_tpu.matching.trie import TopicIndex
from maxmq_tpu.protocol import Subscription
from maxmq_tpu.utils.logger import Logger


@pytest.fixture(autouse=True)
def clean_faults():
    faults.clear()
    yield
    faults.clear()


def small_corpus(n: int = 24) -> TopicIndex:
    idx = TopicIndex()
    for i in range(n):
        idx.subscribe(f"ex{i}", Subscription(filter=f"f/{i}/x", qos=1))
        idx.subscribe(f"pl{i}", Subscription(filter=f"f/{i}/+", qos=0))
    idx.subscribe("hash", Subscription(filter="f/#", qos=2))
    idx.subscribe("sh", Subscription(filter="$share/g/f/1/x", qos=1))
    return idx


def make_engine(idx: TopicIndex) -> SigEngine:
    eng = SigEngine(idx, auto_refresh=False)
    eng.route_small = False      # force the device path on tiny corpora
    return eng


TOPICS = ["f/1/x", "f/7/x", "f/3/zzz", "g/nope", "f/0/x"]


def assert_trie_equal(idx, results, topics=TOPICS):
    for topic, got in zip(topics, results):
        want = idx.subscribers(topic)
        assert normalize(got) == normalize(want), topic


# -- the registry itself ----------------------------------------------


def test_registry_counts_are_deterministic():
    reg = faults.FaultRegistry()
    reg.arm("x", "raise", count=2)
    for _ in range(2):
        with pytest.raises(faults.InjectedFault):
            reg.fire("x")
    assert reg.fire("x") is False          # self-disarmed after 2
    assert reg.fired["x"] == 2
    # FIFO scripting: raise twice, then an action-mode entry
    reg.arm("y", "raise", count=1)
    reg.arm("y", "drop", count=1)
    with pytest.raises(faults.InjectedFault):
        reg.fire("y")
    assert reg.fire("y") is True
    assert reg.fire("y") is False


def test_registry_env_spec_parsing():
    reg = faults.FaultRegistry()
    reg.arm_from_spec("a.b:raise:2, c.d:hang:1:0.001 ,e.f:exit")
    assert reg.armed("a.b") and reg.armed("c.d") and reg.armed("e.f")
    t0 = time.perf_counter()
    assert reg.fire("c.d") is True         # hang mode sleeps delay_s
    assert time.perf_counter() - t0 < 0.5
    assert reg.fire("e.f") is True         # action mode returns True
    with pytest.raises(ValueError):
        reg.arm_from_spec("missing-mode")


# -- rung 2: device exception -> trie hedge, bit-equal ----------------


def test_device_exception_answers_bit_equal_from_trie():
    idx = small_corpus()
    sup = SupervisedMatcher(make_engine(idx), deadline_ms=0,
                            breaker_threshold=100)
    assert_trie_equal(idx, sup.subscribers_batch(TOPICS))   # healthy
    faults.arm(faults.DEVICE_MATCH, "raise", count=-1)
    assert_trie_equal(idx, sup.subscribers_batch(TOPICS))   # degraded
    assert sup.fallbacks_by_reason["error"] == len(TOPICS)
    assert sup.breaker_state == BREAKER_CLOSED              # under threshold
    faults.clear()
    assert_trie_equal(idx, sup.subscribers_batch(TOPICS))   # healed
    assert sup.fallbacks_by_reason["error"] == len(TOPICS)  # no new ones


# -- rung 1: hang past the per-batch deadline -------------------------


async def test_hang_past_deadline_served_from_trie():
    idx = small_corpus()
    eng = make_engine(idx)
    # warm the XLA compile OUTSIDE the deadline window: the supervisor
    # deadlines real calls, and the first-call compile is boot work the
    # production path pays at the quiescent point (warm_buckets)
    await asyncio.get_running_loop().run_in_executor(
        None, eng.subscribers_fixed_batch, ["f/1/x"])
    batcher = MicroBatcher(eng, window_us=0, cpu_bypass=False)
    sup = SupervisedMatcher(batcher, deadline_ms=100,
                            breaker_threshold=100)
    got = await sup.enqueue("f/1/x")                        # healthy
    assert normalize(got) == normalize(idx.subscribers("f/1/x"))
    faults.arm(faults.DEVICE_MATCH, "hang", count=-1, delay_s=0.5)
    t0 = time.perf_counter()
    got = await sup.enqueue("f/7/x")
    took = time.perf_counter() - t0
    assert normalize(got) == normalize(idx.subscribers("f/7/x"))
    assert took < 0.45, took               # answered by the deadline,
    assert sup.deadline_fallbacks == 1     # not the 500ms hang
    faults.clear()
    await asyncio.sleep(0.6)               # drain the hung executor call
    await batcher.close()


def test_sync_deadline_served_from_trie():
    idx = small_corpus()
    eng = make_engine(idx)
    eng.subscribers_batch(TOPICS)          # warm the compile
    sup = SupervisedMatcher(eng, deadline_ms=100,
                            breaker_threshold=100)
    faults.arm(faults.DEVICE_MATCH, "hang", count=1, delay_s=0.5)
    t0 = time.perf_counter()
    results = sup.subscribers_batch(TOPICS)
    assert time.perf_counter() - t0 < 0.45
    assert_trie_equal(idx, results)
    assert sup.fallbacks_by_reason["deadline"] == len(TOPICS)
    time.sleep(0.5)                        # let the hung thread finish


# -- rung 3+4: breaker trip and half-open reprobe ---------------------


def test_breaker_trips_then_half_open_reprobe_restores():
    idx = small_corpus()
    sup = SupervisedMatcher(make_engine(idx), deadline_ms=0,
                            breaker_threshold=3, breaker_window_s=10.0,
                            backoff_initial_s=0.15, backoff_max_s=0.6)
    faults.arm(faults.DEVICE_MATCH, "raise", count=-1)
    for _ in range(3):                     # threshold failures...
        assert_trie_equal(idx, sup.subscribers_batch(TOPICS))
    assert sup.breaker_state == BREAKER_OPEN    # ...trip the breaker
    assert sup.breaker_trips == 1
    # open: answered from the trie with NO device call
    fired_before = faults.REGISTRY.fired.get(faults.DEVICE_MATCH, 0)
    assert_trie_equal(idx, sup.subscribers_batch(TOPICS))
    assert faults.REGISTRY.fired.get(faults.DEVICE_MATCH, 0) \
        == fired_before
    assert sup.fallbacks_by_reason["breaker_open"] == len(TOPICS)
    # fault still present at the first reprobe: re-opens, backoff doubles
    time.sleep(0.2)
    assert_trie_equal(idx, sup.subscribers_batch(TOPICS))
    assert sup.breaker_state == BREAKER_OPEN
    assert sup._backoff == pytest.approx(0.3)
    # fault clears; the next reprobe after the backoff restores the path
    faults.clear()
    time.sleep(0.35)
    assert_trie_equal(idx, sup.subscribers_batch(TOPICS))
    assert sup.breaker_state == BREAKER_CLOSED
    assert sup.breaker_recoveries == 1
    assert sup.degraded_seconds > 0.3


# -- recompile failure: crash-safe table swap -------------------------


def test_recompile_failure_keeps_last_good_tables():
    idx = small_corpus()
    eng = make_engine(idx)
    sup = SupervisedMatcher(eng, deadline_ms=0, breaker_threshold=100)
    v0 = eng.tables.version
    idx.subscribe("late", Subscription(filter="f/9/late", qos=0))
    faults.arm(faults.DEVICE_RECOMPILE, "raise", count=2)
    assert sup.refresh(force=True) is False     # swallowed, counted
    assert sup.refresh(force=True) is False
    assert sup.refresh_failures == 2
    assert eng.tables.version == v0             # last-good still live
    # matches stay EXACT through the stale window (journal overlay)
    topics = TOPICS + ["f/9/late"]
    for topic, got in zip(topics, sup.subscribers_batch(topics)):
        assert normalize(got) == normalize(idx.subscribers(topic)), topic
    # fault exhausted: the next refresh swaps in fresh tables
    assert sup.refresh(force=True) is True
    assert eng.tables.version > v0


# -- matcher-service socket drop --------------------------------------


async def test_service_socket_drop_end_to_end(tmp_path):
    from maxmq_tpu.matching.service import MatcherService, ServiceMatcher

    def svc_engine(i):
        e = SigEngine(i)                   # auto-refresh: service-owned
        e.route_small = False
        return MicroBatcher(e, window_us=0, cpu_bypass=False)

    path = str(tmp_path / "m.sock")
    idx = small_corpus()
    svc = MatcherService(path, engine_factory=svc_engine)
    await svc.start()
    try:
        m = ServiceMatcher(path)
        m.RECONNECT_BACKOFF_INITIAL = 0.02
        await m.connect()

        def reseed(mm):                    # as attach_matcher_service
            for cid, sub in idx.walk_subscriptions():
                mm.forward_subscribe(cid, sub)

        m._reseed = reseed
        reseed(m)
        sup = SupervisedMatcher(m, index=idx, deadline_ms=10_000,
                                breaker_threshold=100)
        got = await sup.enqueue("f/1/x")        # healthy round trip
        assert normalize(got) == normalize(idx.subscribers("f/1/x"))
        # drop the socket server-side on the next frame: the pending
        # match errors, the supervisor answers from the trie
        faults.arm(faults.SERVICE_SOCKET, "drop", count=1)
        got = await sup.enqueue("f/7/x")
        assert normalize(got) == normalize(idx.subscribers("f/7/x"))
        assert sup.error_fallbacks >= 1
        # next enqueue sees the dead transport: trie again, and it kicks
        # the background reconnect loop (capped backoff + jitter)
        got = await sup.enqueue("f/3/zzz")
        assert normalize(got) == normalize(idx.subscribers("f/3/zzz"))
        # the transport fast-fails the ServiceMatcher counts in its own
        # ``fallbacks`` are the SAME events the supervisor counts as
        # reason="error" — they must not also appear as "overflow"
        assert sup.fallbacks_by_reason["overflow"] == 0
        assert sup.fallbacks == (sup.error_fallbacks
                                 + sup.deadline_fallbacks
                                 + sup.breaker_fallbacks)
        await asyncio.sleep(0.4)           # loop reconnects + reseeds
        served_before = svc.matches_served
        got = await sup.enqueue("f/0/x")
        assert normalize(got) == normalize(idx.subscribers("f/0/x"))
        assert svc.matches_served > served_before
        assert m.reconnects >= 1
        assert m.reconnect_attempts >= 1
        await m.close()
    finally:
        await svc.close()


# -- pool-worker death: supervised respawn + counter ------------------


async def test_pool_worker_restart_counted_and_exported():
    from maxmq_tpu.broker.workers import PoolStats, _supervise_workers
    from maxmq_tpu.metrics import Registry, register_pool_metrics

    class FakeProc:
        def __init__(self, rc=None):
            self.rc = rc

        def poll(self):
            return self.rc

    procs = [FakeProc(rc=-9), FakeProc(rc=None)]    # slot 0 was killed
    respawned = []

    def spawn(i):
        respawned.append(i)
        return FakeProc(rc=None)

    stats = PoolStats()
    boot = Logger(out=io.StringIO(), fmt="json").with_prefix("pool")
    task = asyncio.get_running_loop().create_task(
        _supervise_workers(procs, spawn, boot, stats=stats,
                           interval=0.02))
    await asyncio.sleep(0.2)
    task.cancel()
    assert respawned == [0]                # crashed slot respawned once
    assert procs[0].rc is None             # live replacement installed
    assert stats.worker_restarts == 1
    reg = Registry()
    register_pool_metrics(reg, stats)
    assert "maxmq_pool_worker_restarts_total 1" in reg.expose()


# -- observability: the new metric family renders ---------------------


def test_breaker_metrics_exposed():
    from maxmq_tpu.broker import Broker, BrokerOptions, Capabilities
    from maxmq_tpu.metrics import Registry, register_broker_metrics

    broker = Broker(BrokerOptions(
        capabilities=Capabilities(sys_topic_interval=0)))
    for cid, sub in small_corpus().walk_subscriptions():
        broker.topics.subscribe(cid, sub)
    eng = make_engine(broker.topics)
    sup = SupervisedMatcher(MicroBatcher(eng), index=broker.topics,
                            deadline_ms=0, breaker_threshold=2,
                            backoff_initial_s=30.0)
    broker.attach_matcher(sup)
    faults.arm(faults.DEVICE_MATCH, "raise", count=-1)
    for _ in range(2):
        sup.subscribers_batch(TOPICS)      # trip the breaker
    sup.subscribers_batch(TOPICS)          # breaker-open fallbacks
    reg = Registry()
    register_broker_metrics(reg, broker)
    text = reg.expose()
    assert "maxmq_matcher_breaker_state 1" in text          # open
    assert "maxmq_matcher_breaker_trips_total 1" in text
    assert 'maxmq_matcher_fallbacks_total{reason="error"} 10' in text
    assert ('maxmq_matcher_fallbacks_total{reason="breaker_open"} 5'
            in text)
    assert 'maxmq_matcher_fallbacks_total{reason="overflow"} 0' in text
    assert "maxmq_matcher_degraded_seconds_total" in text
    assert "maxmq_matcher_refresh_failures_total 0" in text
    assert "maxmq_matcher_batch_errors_total" in text
    assert "maxmq_broker_publish_trie_degraded_total 0" in text


# -- end to end: a live MQTT publish delivers through every fault -----


async def test_publish_delivers_through_device_faults():
    """The acceptance bar: with the device path raising on every call,
    a real client's publish still delivers to the right subscribers
    (served bit-equal from the trie), the breaker trips, and clearing
    the fault restores the device path after the backoff."""
    async with running_broker() as broker:
        sub_client = await connect(broker, "s1")
        await sub_client.subscribe(("e2e/+/t", 1))
        # build (and warm) the engine AFTER the subscription exists, and
        # pin the tables (auto_refresh=False) so no mid-test rotation
        # re-pays an XLA compile against the 2s deadline; later changes
        # would be served exactly via the journal overlay
        eng = SigEngine(broker.topics, auto_refresh=False)
        eng.route_small = False
        await asyncio.get_running_loop().run_in_executor(
            None, eng.subscribers_fixed_batch, ["e2e/a/t"])
        batcher = MicroBatcher(eng, window_us=0, cpu_bypass=False)
        sup = SupervisedMatcher(batcher, index=broker.topics,
                                deadline_ms=2_000, breaker_threshold=3,
                                backoff_initial_s=0.1,
                                backoff_max_s=0.2)
        broker.attach_matcher(sup)

        pub = await connect(broker, "p1")
        await pub.publish("e2e/a/t", b"healthy", qos=1)
        msg = await sub_client.next_message(timeout=10)
        assert (msg.topic, msg.payload) == ("e2e/a/t", b"healthy")

        faults.arm(faults.DEVICE_MATCH, "raise", count=-1)
        for i in range(4):                 # past the breaker threshold
            await pub.publish(f"e2e/f{i}/t", b"faulted-%d" % i, qos=1)
        for i in range(4):
            msg = await sub_client.next_message(timeout=10)
            assert msg.payload == b"faulted-%d" % i    # order preserved
        assert sup.breaker_state == BREAKER_OPEN
        assert sup.fallbacks_by_reason["error"] >= 3

        faults.clear()
        await asyncio.sleep(0.25)          # backoff expires
        await pub.publish("e2e/r/t", b"recovered", qos=1)
        msg = await sub_client.next_message(timeout=10)
        assert msg.payload == b"recovered"
        assert sup.breaker_state == BREAKER_CLOSED
        assert sup.breaker_recoveries == 1

        await pub.disconnect()
        await sub_client.disconnect()
        await batcher.close()
