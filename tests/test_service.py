"""Matcher service (matching/service.py): the chip-owning process
serving matches over a unix socket to broker clients (ADR 005/006)."""

import asyncio
import os
import tempfile

import pytest

from test_broker_system import connect, running_broker
from test_nfa_parity import normalize

from maxmq_tpu.matching.batcher import MicroBatcher
from maxmq_tpu.matching.service import (MatcherService, ServiceMatcher,
                                        attach_matcher_service)
from maxmq_tpu.matching.sig import SigEngine
from maxmq_tpu.matching.trie import TopicIndex
from maxmq_tpu.protocol import Subscription


def _sock_path() -> str:
    return os.path.join(tempfile.mkdtemp(prefix="maxmq-svc-"), "m.sock")


async def test_service_matches_and_tracks_subscriptions():
    path = _sock_path()
    svc = MatcherService(path)
    await svc.start()
    try:
        m = ServiceMatcher(path)
        await m.connect()
        m.forward_subscribe("c1", Subscription(filter="a/+/c", qos=1))
        m.forward_subscribe("c2", Subscription(filter="a/#"))
        m.forward_subscribe(
            "c3", Subscription(filter="$share/g1/a/b/c", qos=2))
        # mirror index for the expected answer
        want_idx = TopicIndex()
        want_idx.subscribe("c1", Subscription(filter="a/+/c", qos=1))
        want_idx.subscribe("c2", Subscription(filter="a/#"))
        want_idx.subscribe("c3",
                           Subscription(filter="$share/g1/a/b/c", qos=2))
        for topic in ("a/b/c", "a/x/c", "a", "b/c"):
            got = await m.subscribers_async(topic)
            assert normalize(got) == normalize(want_idx.subscribers(topic)), \
                topic
        # ops are ordered before matches on the same connection
        m.forward_unsubscribe("c2", "a/#")
        got = await m.subscribers_async("a/zzz")
        assert "c2" not in got.subscriptions
        m.forward_drop("c1")
        got = await m.subscribers_async("a/x/c")
        assert "c1" not in got.subscriptions
        assert svc.matches_served >= 6
        await m.close()
    finally:
        await svc.close()


async def test_two_clients_share_one_service():
    """Two broker processes' worth of clients coalesce on one engine and
    see each other's subscriptions (the pool-worker shape)."""
    path = _sock_path()
    svc = MatcherService(
        path, engine_factory=lambda idx: MicroBatcher(
            SigEngine(idx), window_us=0))
    await svc.start()
    try:
        m1, m2 = ServiceMatcher(path), ServiceMatcher(path)
        await m1.connect()
        await m2.connect()
        m1.forward_subscribe("w1-cl", Subscription(filter="t/+"))
        await m1.subscribers_async("t/x")     # barrier: op applied
        got = await m2.subscribers_async("t/x")
        assert "w1-cl" in got.subscriptions
        await m1.close()
        await m2.close()
    finally:
        await svc.close()


async def test_broker_attached_to_matcher_service():
    """Full path: MQTT clients against a broker whose matching runs in
    the service process-equivalent (same loop here; the socket is real)."""
    path = _sock_path()
    svc = MatcherService(path)
    await svc.start()
    try:
        async with running_broker() as broker:
            matcher = await attach_matcher_service(broker, path)
            sub = await connect(broker, "svc-sub")
            await sub.subscribe(("svc/+/x", 0))
            pub = await connect(broker, "svc-pub")
            await pub.publish("svc/a/x", b"hello")
            msg = await sub.next_message(timeout=10)
            assert msg.topic == "svc/a/x" and msg.payload == b"hello"
            # unsubscribe stops delivery through the service too
            await sub.unsubscribe("svc/+/x")
            await pub.publish("svc/a/x", b"again")
            await asyncio.sleep(0.2)
            assert sub.messages.empty()
            await sub.disconnect()
            await pub.disconnect()
            await matcher.close()
    finally:
        await svc.close()


async def test_run_server_with_service_matcher(tmp_path):
    """Bootstrap path: matcher = "service" connects the broker to an
    external matcher service socket (maxmq matcher-service)."""
    import asyncio as aio

    from maxmq_tpu.bootstrap import run_server
    from maxmq_tpu.mqtt_client import MQTTClient
    from maxmq_tpu.utils.config import Config
    from test_bootstrap import quiet_logger

    path = str(tmp_path / "m.sock")
    svc = MatcherService(path)
    await svc.start()
    try:
        conf = Config(mqtt_tcp_address="127.0.0.1:18845",
                      metrics_enabled=False, matcher="service",
                      matcher_socket=path, mqtt_sys_topic_interval=0)
        ready, stop = aio.Event(), aio.Event()
        task = aio.create_task(
            run_server(conf, quiet_logger(), ready=ready, stop=stop))
        await aio.wait_for(ready.wait(), timeout=10)
        c = MQTTClient(client_id="svc-boot")
        await c.connect("127.0.0.1", 18845)
        await c.subscribe(("sb/#", 0))
        await c.publish("sb/x", b"via-service")
        msg = await c.next_message(timeout=5)
        assert msg.payload == b"via-service"
        assert svc.matches_served >= 1
        await c.disconnect()
        stop.set()
        await aio.wait_for(task, timeout=15)
    finally:
        await svc.close()


async def test_service_loss_degrades_to_trie_then_reconnects(tmp_path):
    """Service crash mid-flight: publishes degrade to the broker's CPU
    trie (no hangs, no drops); a restarted service at the same path is
    picked up by the background reconnect and re-seeded."""
    path = str(tmp_path / "m.sock")
    svc = MatcherService(path)
    await svc.start()
    async with running_broker() as broker:
        matcher = await attach_matcher_service(broker, path)
        sub = await connect(broker, "rl-sub")
        await sub.subscribe(("rl/#", 0))
        pub = await connect(broker, "rl-pub")
        await pub.publish("rl/1", b"a")
        assert (await sub.next_message(timeout=10)).payload == b"a"

        await svc.close()                      # service dies
        await asyncio.sleep(0.1)
        await pub.publish("rl/2", b"b")        # trie fallback delivers
        assert (await sub.next_message(timeout=10)).payload == b"b"

        svc2 = MatcherService(path)            # service comes back
        await svc2.start()
        try:
            for i in range(50):                # reconnect is lazy: each
                await pub.publish(f"rl/r{i}", b"c")   # publish retries
                await sub.next_message(timeout=10)
                if svc2.matches_served:
                    break
                await asyncio.sleep(0.05)
            assert svc2.matches_served > 0, "reconnect never happened"
            assert svc2.subs_applied >= 1      # re-seeded rl/# for rl-sub
        finally:
            await svc2.close()
        await sub.disconnect()
        await pub.disconnect()
        await matcher.close()


async def test_attach_seeds_preexisting_subscriptions(tmp_path):
    """Subscriptions installed WITHOUT the subscribe hooks (the storage
    restore path) must still reach the service via the index walk."""
    path = str(tmp_path / "m.sock")
    svc = MatcherService(path)
    await svc.start()
    try:
        async with running_broker() as broker:
            # as _restore_from_storage does: direct index install
            broker.topics.subscribe(
                "persisted-cl", Subscription(filter="pr/+", qos=1))
            matcher = await attach_matcher_service(broker, path)
            got = await matcher.subscribers_async("pr/x")
            assert "persisted-cl" in got.subscriptions
            await matcher.close()
    finally:
        await svc.close()


async def test_service_matcher_topic_cache(tmp_path):
    """Repeated topics resolve from the version-keyed cache without a
    socket round trip; a subscription change invalidates."""
    path = str(tmp_path / "m.sock")
    svc = MatcherService(path)
    await svc.start()
    try:
        async with running_broker() as broker:
            matcher = await attach_matcher_service(broker, path)
            sub = await connect(broker, "tc-sub")
            await sub.subscribe(("tc/#", 0))
            r1 = await matcher.subscribers_async("tc/x")
            served = svc.matches_served
            r2 = await matcher.subscribers_async("tc/x")   # cache hit
            assert matcher.cache_hits == 1
            assert svc.matches_served == served            # no round trip
            assert "tc-sub" in r1.subscriptions and r1 == r2
            await sub.subscribe(("tc/x", 1))               # version bump
            r3 = await matcher.subscribers_async("tc/x")
            assert svc.matches_served > served
            assert r3.subscriptions["tc-sub"].qos == 1
            await sub.disconnect()
            await matcher.close()
    finally:
        await svc.close()


async def test_takeover_refcounted_across_connections():
    """Cross-worker session takeover (ADVICE r03 high): worker B
    re-subscribes (cid, filter) on its connection, then worker A's
    takeover-driven drop arrives — the index entry must survive until
    the LAST owning connection releases it, in every op interleaving."""
    path = _sock_path()
    svc = MatcherService(path)
    await svc.start()
    try:
        a, b = ServiceMatcher(path), ServiceMatcher(path)
        await a.connect()
        await b.connect()
        sub = Subscription(filter="tk/+", qos=1)
        a.forward_subscribe("cl", sub)
        await a.subscribers_async("tk/x")          # barrier: op applied
        # takeover: B re-subscribes, then A's stale drop arrives
        b.forward_subscribe("cl", sub)
        await b.subscribers_async("tk/x")
        a.forward_drop("cl")
        await a.subscribers_async("tk/x")
        got = await b.subscribers_async("tk/x")
        assert "cl" in got.subscriptions, \
            "stale drop removed a re-owned subscription"
        # A's connection closing entirely must not purge B's entry either
        await a.close()
        await asyncio.sleep(0.1)
        got = await b.subscribers_async("tk/y")
        assert "cl" in got.subscriptions
        # the LAST owner's drop does release the entry
        b.forward_drop("cl")
        got = await b.subscribers_async("tk/x")
        assert "cl" not in got.subscriptions
        assert svc._owners == {}, "owner refs leaked"
        await b.close()
    finally:
        await svc.close()


async def test_unsub_is_authoritative_across_owners():
    """An explicit UNSUB stops matching IMMEDIATELY even while a stale
    connection still holds an ownership ref (a wedged old worker must
    not keep an unsubscribed client receiving deliveries) — and the
    stale owner's eventual death must not tear down a LATER re-subscribe
    (generation guard)."""
    path = _sock_path()
    svc = MatcherService(path)
    await svc.start()
    try:
        a, b = ServiceMatcher(path), ServiceMatcher(path)
        await a.connect()
        await b.connect()
        sub = Subscription(filter="ur/+", qos=1)
        a.forward_subscribe("cl", sub)             # stale-owner-to-be
        await a.subscribers_async("ur/x")
        b.forward_subscribe("cl", sub)             # takeover re-own
        await b.subscribers_async("ur/x")
        b.forward_unsubscribe("cl", "ur/+")        # client unsubscribed
        got = await b.subscribers_async("ur/x")
        assert "cl" not in got.subscriptions, \
            "unsub must take effect immediately, not at last-owner death"
        # client re-subscribes on B; A's BUFFERED unsub flushes late —
        # generation-stale, it must not tear down B's live entry
        b.forward_subscribe("cl", sub)
        await b.subscribers_async("ur/x")
        a.forward_unsubscribe("cl", "ur/+")
        await a.subscribers_async("ur/x")
        got = await b.subscribers_async("ur/x")
        assert "cl" in got.subscriptions, \
            "stale buffered unsub removed a re-owned entry"
        # ... and A (wedged all along) finally dies — same guarantee
        await a.close()
        await asyncio.sleep(0.1)
        got = await b.subscribers_async("ur/y")
        assert "cl" in got.subscriptions, \
            "stale owner death removed a re-subscribed entry"
        await b.close()
    finally:
        await svc.close()


async def test_protocol_error_closes_transport_before_reconnect():
    """ADVICE r03 medium: a protocol error must CLOSE the old transport
    (not just null it) so the server purges the dead connection's state;
    the reconnect reseed then repopulates it without fd leaks."""
    path = _sock_path()
    svc = MatcherService(path)
    await svc.start()
    async with running_broker() as broker:
        matcher = await attach_matcher_service(broker, path)
        sub = await connect(broker, "pe-sub")
        await sub.subscribe(("pe/#", 0))
        await matcher.subscribers_async("pe/x")    # round trip ok
        old_writer = matcher._writer
        # inject garbage into the reader path by closing the server side:
        # force a protocol error instead via a malformed internal frame
        matcher._reader.feed_data(b"\x00\x00\x00\x02\x63{")  # bad frame
        await asyncio.sleep(0.2)
        assert matcher._writer is None
        assert old_writer.is_closing(), "old transport leaked"
        # next publish degrades to trie and kicks a reconnect that
        # replays subscriptions on a FRESH connection
        pub = await connect(broker, "pe-pub")
        for i in range(50):
            await pub.publish(f"pe/r{i}", b"x")
            await sub.next_message(timeout=10)
            if matcher.reconnects:
                break
            await asyncio.sleep(0.05)
        assert matcher.reconnects >= 1
        got = await matcher.subscribers_async("pe/q")
        assert "pe-sub" in got.subscriptions
        await sub.disconnect()
        await pub.disconnect()
        await matcher.close()
    await svc.close()


async def test_cli_matcher_service_command(tmp_path):
    """`maxmq matcher-service` serves a usable socket (subprocess)."""
    import os
    import signal
    import subprocess
    import sys

    path = str(tmp_path / "cli.sock")
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "maxmq_tpu", "matcher-service",
         "--socket", path],
        cwd="/root/repo", env=env, stderr=subprocess.PIPE)
    try:
        for _ in range(100):
            if os.path.exists(path):
                break
            await asyncio.sleep(0.1)
        else:
            raise AssertionError("service socket never appeared")
        m = ServiceMatcher(path)
        await m.connect()
        m.forward_subscribe("cli-c", Subscription(filter="cli/+"))
        got = await m.subscribers_async("cli/x")
        assert "cli-c" in got.subscriptions
        await m.close()
    finally:
        proc.send_signal(signal.SIGINT)
        proc.wait(timeout=10)


async def test_service_encode_memo_reuses_fragments():
    """Shared match results serialize once: repeated topics (same cached
    result object) must reuse the JSON fragment, with byte-identical
    decoded answers either way."""
    path = _sock_path()
    svc = MatcherService(path)
    await svc.start()
    try:
        m = ServiceMatcher(path)
        await m.connect()
        # no index attached -> the client topic cache stays off and
        # every match goes to the wire
        for i in range(40):
            m.forward_subscribe(f"f{i}", Subscription(filter="em/#",
                                                      qos=1))
        first = await m.subscribers_async("em/x")
        assert svc.enc_hits == 0
        for _ in range(3):
            again = await m.subscribers_async("em/x")
            assert normalize(again) == normalize(first)
        assert svc.enc_hits >= 3, svc.enc_hits
        # a subscription change rotates the result object -> fresh frag
        m.forward_subscribe("late", Subscription(filter="em/x", qos=0))
        hits_before = svc.enc_hits
        got = await m.subscribers_async("em/x")
        assert "late" in got.subscriptions
        assert svc.enc_hits == hits_before  # new result: memo miss
        await m.close()
    finally:
        await svc.close()


async def test_restart_mid_match_reseed_race(tmp_path):
    """The ADR-011 reconnect/reseed race: restart the service while a
    match is IN FLIGHT. The pending future must error (trie fallback
    upstream), the client result cache must be invalidated, and the
    reconnect must replay the live subscription set exactly once."""
    from maxmq_tpu.matching.trie import subs_version

    path = str(tmp_path / "m.sock")

    class HangingMatcher:                    # never answers: the match
        async def subscribers_async(self, topic):   # is mid-flight when
            await asyncio.Event().wait()            # the service dies

    svc = MatcherService(path, engine_factory=lambda idx: HangingMatcher())
    await svc.start()

    idx = TopicIndex()
    idx.subscribe("rc1", Subscription(filter="rr/+", qos=1))
    idx.subscribe("rc2", Subscription(filter="rr/#", qos=0))
    m = ServiceMatcher(path)
    m.RECONNECT_BACKOFF_INITIAL = 0.02
    m.index = idx
    reseeds = []

    def reseed(mm):
        reseeds.append(1)
        for cid, sub in idx.walk_subscriptions():
            mm.forward_subscribe(cid, sub)

    m._reseed = reseed
    await m.connect()
    reseed(m)                                # attach-time seed (as prod)
    ver = subs_version(idx)
    m._cache.put("rr/x", ver, idx.subscribers("rr/x"))   # warm cache

    fut = m.enqueue("rr/x2")                 # in flight (never answered)
    await asyncio.sleep(0.1)
    assert not fut.done() and m._pending
    await svc.close()                        # restart begins mid-match
    with pytest.raises((ConnectionError, RuntimeError)):
        await asyncio.wait_for(fut, timeout=5)   # pending future errors
    assert not m._pending
    assert m._cache.get("rr/x", ver) is None     # cache invalidated

    svc2 = MatcherService(path)              # service comes back
    await svc2.start()
    try:
        reseeds.clear()
        with pytest.raises((ConnectionError, RuntimeError)):
            await m.enqueue("rr/kick")       # kicks the reconnect loop
        for _ in range(100):
            if m.reconnects and svc2.subs_applied >= 2:
                break
            await asyncio.sleep(0.05)
        assert sum(reseeds) == 1             # replayed exactly once
        assert svc2.subs_applied == 2        # the live set, no extras
        got = await m.subscribers_async("rr/y")
        assert set(got.subscriptions) == {"rc1", "rc2"}
    finally:
        await m.close()
        await svc2.close()


async def test_reconnect_backoff_retries_while_quiet(tmp_path):
    """The reconnect loop keeps retrying under capped exponential
    backoff while traffic is quiet — the old behavior gave up after one
    OSError and waited for the next enqueue, so a silent broker stayed
    disconnected as long as it stayed silent."""
    path = str(tmp_path / "m.sock")
    svc = MatcherService(path)
    await svc.start()
    m = ServiceMatcher(path)
    m.RECONNECT_BACKOFF_INITIAL = 0.02
    m.RECONNECT_BACKOFF_MAX = 0.1
    await m.connect()
    await m.subscribers_async("warm/x")      # connection fully accepted
    await svc.close()                        # service gone
    with pytest.raises((ConnectionError, RuntimeError)):
        await m.enqueue("q/x")               # ONE kick, then silence
    await asyncio.sleep(0.3)                 # loop retries on its own
    assert m.reconnect_attempts >= 2, m.reconnect_attempts
    svc2 = MatcherService(path)
    await svc2.start()
    try:
        for _ in range(100):                 # no further enqueues: the
            if m.reconnects:                 # loop alone reconnects
                break
            await asyncio.sleep(0.05)
        assert m.reconnects == 1
        assert m._writer is not None and not m._writer.is_closing()
    finally:
        await m.close()
        await svc2.close()
