"""Black-box system tests: a real broker on a real TCP socket, exercised by
the in-repo MQTT client. Mirrors the reference's paho system suite
(tests/system/mqtt_test.go): connect/disconnect, keepalive, wildcard
subscribe with granted QoS, unsubscribe, QoS0 roundtrip, QoS1/QoS2
offline-delivery, plus retained/will/takeover/shared-subscription scenarios.
"""

import asyncio
from contextlib import asynccontextmanager

import pytest

from maxmq_tpu.broker import Broker, BrokerOptions, Capabilities, TCPListener
from maxmq_tpu.hooks import AllowHook
from maxmq_tpu.mqtt_client import MQTTClient
from maxmq_tpu.protocol import Will


@asynccontextmanager
async def running_broker(**caps):
    caps.setdefault("sys_topic_interval", 0)
    b = Broker(BrokerOptions(capabilities=Capabilities(**caps)))
    b.add_hook(AllowHook())
    listener = b.add_listener(TCPListener("t1", "127.0.0.1:0"))
    await b.serve()
    b.test_port = listener._server.sockets[0].getsockname()[1]
    try:
        yield b
    finally:
        await b.close()


async def connect(broker, client_id="", version=4, **kw) -> MQTTClient:
    c = MQTTClient(client_id=client_id, version=version, **kw)
    await c.connect("127.0.0.1", broker.test_port)
    return c


async def test_connect_disconnect():
    async with running_broker() as broker:
        c = await connect(broker, "c1")
        assert c.connack.reason_code == 0
        assert c.connack.session_present is False
        assert broker.info.clients_connected == 1
        await c.disconnect()
        await asyncio.sleep(0.05)
        assert broker.info.clients_connected == 0


async def test_keepalive_ping():
    async with running_broker() as broker:
        c = await connect(broker, "c1", keepalive=2)
        for _ in range(3):
            await c.ping()
            await asyncio.sleep(0.05)
        await c.disconnect()


async def test_keepalive_timeout_drops_client():
    async with running_broker(keepalive_grace=0.2) as broker:
        c = await connect(broker, "c1", keepalive=1)
        await c.wait_closed(timeout=5)
        await asyncio.sleep(0.05)
        assert broker.info.clients_connected == 0


async def test_keepalive_clamped_to_maximum():
    """Operator keepalive limit: clamp + v5 ServerKeepAlive [MQTT-3.1.2-21]."""
    async with running_broker(maximum_keepalive=5) as broker:
        c = await connect(broker, "c1", version=5, keepalive=60)
        assert c.connack.properties.server_keep_alive == 5
        assert broker.clients.get("c1").keepalive == 5
        await c.disconnect()
        # keepalive 0 (never drop) is also subject to the operator limit
        c2 = await connect(broker, "c2", version=5, keepalive=0)
        assert c2.connack.properties.server_keep_alive == 5
        await c2.disconnect()


async def test_subscribe_wildcards_granted_qos():
    async with running_broker() as broker:
        c = await connect(broker, "c1")
        granted = await c.subscribe(("sensor/#", 0), ("data/+/raw", 1),
                                    ("exact/topic", 2))
        assert granted == [0, 1, 2]
        assert broker.info.subscriptions == 3


async def test_subscribe_invalid_filter_rejected():
    async with running_broker() as broker:
        c = await connect(broker, "c1", version=5)
        granted = await c.subscribe("bad/#/filter")
        assert granted == [0x8F]


async def test_unsubscribe():
    async with running_broker() as broker:
        c = await connect(broker, "c1")
        await c.subscribe("a/b")
        await c.unsubscribe("a/b")
        await c.publish("a/b", b"after-unsub")
        with pytest.raises(asyncio.TimeoutError):
            await c.next_message(timeout=0.2)


async def test_qos0_roundtrip():
    async with running_broker() as broker:
        s = await connect(broker, "sub")
        p = await connect(broker, "pub")
        await s.subscribe("room/+/temp")
        await p.publish("room/kitchen/temp", b"21.5")
        msg = await s.next_message()
        assert (msg.topic, msg.payload, msg.qos) == \
            ("room/kitchen/temp", b"21.5", 0)


@pytest.mark.parametrize("qos", [1, 2])
async def test_offline_delivery(qos):
    """Persistent session disconnects; messages published meanwhile are
    delivered on reconnect (the reference's headline QoS1/QoS2 scenario)."""
    async with running_broker() as broker:
        s = await connect(broker, "subber", clean_start=False)
        await s.subscribe(("queue/data", qos))
        await s.close()  # network drop, not DISCONNECT: session persists
        await asyncio.sleep(0.05)

        p = await connect(broker, "pubber")
        await p.publish("queue/data", b"while-away", qos=qos)
        await p.disconnect()

        s2 = MQTTClient(client_id="subber", version=4, clean_start=False)
        await s2.connect("127.0.0.1", broker.test_port)
        assert s2.connack.session_present is True
        msg = await s2.next_message()
        assert msg.payload == b"while-away"
        assert msg.qos == qos
        await s2.disconnect()


async def test_qos2_exactly_once_dedup():
    async with running_broker() as broker:
        s = await connect(broker, "sub")
        p = await connect(broker, "pub")
        await s.subscribe(("once/t", 2))
        for i in range(3):
            await p.publish("once/t", f"m{i}".encode(), qos=2)
        got = [await s.next_message() for _ in range(3)]
        assert [m.payload for m in got] == [b"m0", b"m1", b"m2"]
        with pytest.raises(asyncio.TimeoutError):
            await s.next_message(timeout=0.2)


async def test_retained_message_delivery():
    async with running_broker() as broker:
        p = await connect(broker, "pub")
        await p.publish("config/node1", b"v1", retain=True)
        await asyncio.sleep(0.05)
        s = await connect(broker, "sub")
        await s.subscribe("config/+")
        msg = await s.next_message()
        assert msg.payload == b"v1"
        assert msg.retain is True
        # clearing: empty retained payload
        await p.publish("config/node1", b"", retain=True)
        await asyncio.sleep(0.05)
        s2 = await connect(broker, "sub2")
        await s2.subscribe("config/+")
        with pytest.raises(asyncio.TimeoutError):
            await s2.next_message(timeout=0.2)


async def test_will_on_abnormal_disconnect():
    async with running_broker() as broker:
        s = await connect(broker, "watcher")
        await s.subscribe("wills/+")
        w = await connect(broker, "doomed",
                          will=Will(topic="wills/doomed", payload=b"gone"))
        await w.close()  # abrupt close -> will fires
        msg = await s.next_message()
        assert (msg.topic, msg.payload) == ("wills/doomed", b"gone")


async def test_no_will_on_clean_disconnect():
    async with running_broker() as broker:
        s = await connect(broker, "watcher")
        await s.subscribe("wills/+")
        w = await connect(broker, "polite",
                          will=Will(topic="wills/polite", payload=b"gone"))
        await w.disconnect()
        with pytest.raises(asyncio.TimeoutError):
            await s.next_message(timeout=0.2)


async def test_session_takeover():
    async with running_broker() as broker:
        c1 = await connect(broker, "same-id", version=5)
        c2 = await connect(broker, "same-id", version=5)
        await c1.wait_closed()
        assert c1.disconnect_packet is not None
        assert c1.disconnect_packet.reason_code == 0x8E  # session taken over
        await c2.ping()  # new connection is live
        await c2.disconnect()


async def test_shared_subscription_round_robin():
    async with running_broker() as broker:
        a = await connect(broker, "worker-a", version=5)
        b = await connect(broker, "worker-b", version=5)
        p = await connect(broker, "pub", version=5)
        await a.subscribe("$share/grp/jobs")
        await b.subscribe("$share/grp/jobs")
        for i in range(4):
            await p.publish("jobs", f"j{i}".encode())
        await asyncio.sleep(0.1)
        got_a, got_b = a.messages.qsize(), b.messages.qsize()
        assert got_a + got_b == 4
        assert got_a == 2 and got_b == 2  # round-robin fairness


async def test_dollar_sys_subscription():
    async with running_broker() as broker:
        c = await connect(broker, "c1")
        await c.subscribe("$SYS/#")
        broker.publish_sys_topics()
        msg = await c.next_message()
        assert msg.topic.startswith("$SYS/")


async def test_clients_cannot_publish_dollar_topics():
    async with running_broker() as broker:
        watcher = await connect(broker, "w")
        await watcher.subscribe("$SYS/#")
        c = await connect(broker, "c1")
        await c.publish("$SYS/broker/version", b"fake")
        with pytest.raises(asyncio.TimeoutError):
            await watcher.next_message(timeout=0.2)


async def test_no_local_v5():
    async with running_broker() as broker:
        c = await connect(broker, "c1", version=5)
        await c.subscribe(("loop/t", 0), no_local=True)
        await c.publish("loop/t", b"self")
        with pytest.raises(asyncio.TimeoutError):
            await c.next_message(timeout=0.2)


async def test_v5_clean_start_discards_session():
    async with running_broker() as broker:
        c = await connect(broker, "cs", version=5, clean_start=False,
                          session_expiry=300)
        await c.subscribe("keep/me")
        await c.close()
        await asyncio.sleep(0.05)
        c2 = MQTTClient(client_id="cs", version=5, clean_start=True)
        await c2.connect("127.0.0.1", broker.test_port)
        assert c2.connack.session_present is False
        await c2.disconnect()


async def test_second_connect_is_protocol_violation():
    async with running_broker() as broker:
        c = await connect(broker, "c1")
        from maxmq_tpu.protocol import FixedHeader, Packet, PacketType as PT
        dup = Packet(fixed=FixedHeader(type=PT.CONNECT), protocol_version=4,
                     client_id="c1", clean_start=True)
        c.writer.write(dup.encode())
        await c.writer.drain()
        await c.wait_closed()  # broker must drop the connection


async def test_inline_publish_api():
    async with running_broker() as broker:
        c = await connect(broker, "c1")
        await c.subscribe("inline/+")
        await broker.publish("inline/x", b"from-server", retain=False)
        msg = await c.next_message()
        assert msg.payload == b"from-server"


async def test_retained_qos_downgrade_and_sub_qos():
    async with running_broker() as broker:
        p = await connect(broker, "pub")
        await p.publish("r/t", b"keep", qos=1, retain=True)
        await asyncio.sleep(0.05)
        s = await connect(broker, "sub")
        await s.subscribe(("r/t", 0))  # subscription qos caps delivery
        msg = await s.next_message()
        assert msg.qos == 0 and msg.payload == b"keep"


async def test_broker_with_nfa_matcher_attached():
    """Full path: PUBLISH over TCP -> NFA engine match -> fan-out."""
    from maxmq_tpu.matching.engine import NFAEngine
    async with running_broker() as broker:
        broker.attach_matcher(NFAEngine(broker.topics))
        s = await connect(broker, "sub", version=5)
        await s.subscribe(("nfa/+/path", 1), ("$share/g/nfa/shared", 0))
        p = await connect(broker, "pub")
        await p.publish("nfa/hot/path", b"via-nfa", qos=1)
        msg = await s.next_message()
        assert (msg.topic, msg.payload, msg.qos) == ("nfa/hot/path", b"via-nfa", 1)
        await p.publish("nfa/shared", b"shared-via-nfa")
        msg = await s.next_message()
        assert msg.payload == b"shared-via-nfa"
        # subscription mutations picked up by auto-refresh
        await s.unsubscribe("nfa/+/path")
        await p.publish("nfa/hot/path", b"after-unsub")
        with pytest.raises(asyncio.TimeoutError):
            await s.next_message(timeout=0.3)


async def test_broker_with_sig_matcher_intents():
    """Full path through DeliveryIntents (ADR 007): PUBLISH over TCP ->
    sig match -> native decode emits intents -> broker fans out from the
    flat entries. Covers plain QoS1, $share exactly-once across two
    group members, NoLocal, and a select_subscribers hook forcing the
    to_set() materialization path."""
    from maxmq_tpu.matching.batcher import MicroBatcher
    from maxmq_tpu.matching.sig import SigEngine
    from maxmq_tpu.native import decode_module
    mod = decode_module()
    if mod is None or not hasattr(mod, "DeliveryIntents"):
        pytest.skip("maxmq_decode extension unavailable")
    async with running_broker() as broker:
        eng = SigEngine(broker.topics)
        eng.emit_intents = True
        eng.route_small = False   # force the device/intents path
        broker.attach_matcher(MicroBatcher(eng, window_us=0))
        s = await connect(broker, "sub", version=5)
        await s.subscribe(("ity/+/path", 1))
        g1 = await connect(broker, "g1", version=5)
        await g1.subscribe(("$share/g/ity/shared", 0))
        g2 = await connect(broker, "g2", version=5)
        await g2.subscribe(("$share/g/ity/shared", 0))
        p = await connect(broker, "pub")
        await p.publish("ity/hot/path", b"via-intents", qos=1)
        msg = await s.next_message()
        assert (msg.topic, msg.payload, msg.qos) == \
            ("ity/hot/path", b"via-intents", 1)
        # $share: exactly one of the two group members per publish
        for i in range(6):
            await p.publish("ity/shared", f"s{i}".encode())
        deadline = asyncio.get_running_loop().time() + 15
        while (g1.messages.qsize() + g2.messages.qsize()) < 6:
            assert asyncio.get_running_loop().time() < deadline, (
                f"shared fan-out delivered "
                f"{g1.messages.qsize() + g2.messages.qsize()}, want 6")
            await asyncio.sleep(0.05)
        await asyncio.sleep(0.2)          # no duplicates trickling in
        assert g1.messages.qsize() + g2.messages.qsize() == 6
        # NoLocal: publisher subscribed no_local must not self-receive
        nl = await connect(broker, "nl", version=5)
        await nl.subscribe(("ity/nl", 0), no_local=True)
        await nl.publish("ity/nl", b"self")
        with pytest.raises(asyncio.TimeoutError):
            await nl.next_message(timeout=0.3)
        # a select_subscribers hook flips the fan-out to to_set()
        from maxmq_tpu.hooks.base import Hook

        class DropAll(Hook):
            id = "drop-all-sel"

            def on_select_subscribers(self, subscribers, packet):
                subscribers.subscriptions.clear()
                return subscribers
        broker.add_hook(DropAll())
        await p.publish("ity/hot/path", b"suppressed", qos=1)
        with pytest.raises(asyncio.TimeoutError):
            await s.next_message(timeout=0.3)


async def test_send_quota_holds_and_releases():
    """v5 receive-maximum flow control: excess QoS1 fan-out parks on the
    held queue and drains as acks return quota."""
    async with running_broker() as broker:
        s = MQTTClient(client_id="slow", version=5)
        s.session_expiry = 0
        await s.connect("127.0.0.1", broker.test_port)
        # advertise a tiny receive maximum by hand-crafting the CONNECT:
        # easier path — reach into the session and shrink the send quota
        sess = broker.clients.get("slow")
        sess.inflight.maximum_send = 1
        sess.inflight.send_quota = 1
        await s.subscribe(("flow/t", 1))
        p = await connect(broker, "pub")
        for i in range(3):
            await p.publish("flow/t", f"m{i}".encode(), qos=1)
        got = [await s.next_message(timeout=3) for _ in range(3)]
        assert sorted(m.payload for m in got) == [b"m0", b"m1", b"m2"]
        assert not sess.held_pids


async def test_select_subscribers_hook_at_scale():
    """Hook-present fan-out at scale (the round-4 verdict's weak spot:
    any installed on_select_subscribers fell back to the merged-set
    rate). A modifying selection hook must ride intents ->
    select_set() with ALIASED Subscription records — a C-side dict
    materialization (cached per row set once it re-hits), never a
    per-publish deep copy — while a declared record-mutator still gets
    full isolation."""
    from maxmq_tpu.hooks.base import Hook
    from maxmq_tpu.matching.batcher import MicroBatcher
    from maxmq_tpu.matching.sig import SigEngine
    from maxmq_tpu.protocol.packets import Subscription as Sub

    async with running_broker() as broker:
        for i in range(20_000):
            broker.topics.subscribe(
                f"synth-{i}", Sub(filter=f"scale/x{i % 4000}/t", qos=0))
        for i in range(8):
            broker.topics.subscribe(f"wild-{i}", Sub(filter="scale/+/t"))
        s = await connect(broker, "real-sub")
        await s.subscribe(("scale/+/t", 0))

        engine = SigEngine(broker.topics)
        engine.emit_intents = True
        engine.route_small = False         # force the device decode path
        broker.attach_matcher(MicroBatcher(engine, window_us=100,
                                           max_batch=64))
        wild0_recs: list = []          # strong refs: id() stays valid
        sizes: list[int] = []

        class DropWild1(Hook):
            id = "drop-wild1"

            def on_select_subscribers(self, subscribers, packet):
                rec = subscribers.subscriptions.get("wild-0")
                if rec is not None:
                    wild0_recs.append(rec)
                subscribers.subscriptions.pop("wild-1", None)
                sizes.append(len(subscribers.subscriptions))
                return subscribers

        broker.add_hook(DropWild1())
        p = await connect(broker, "pub")
        n_pub = 100
        for i in range(n_pub):
            await p.publish(f"scale/x{i}/t", b"m", qos=0)
        got = [await s.next_message(timeout=10) for _ in range(n_pub)]
        assert len(got) == n_pub           # real-sub never dropped
        assert len(sizes) == n_pub         # hook ran on every publish
        # every result: 5 synth matches + 8 wild + real-sub, minus the
        # dropped wild-1
        assert sizes == [5 + 8 + 1 - 1] * n_pub, sizes[:5]
        # the fast-tier contract: records are ALIASED from the matcher's
        # caches — one stored record observed across all 100 publishes.
        # A per-publish deep copy would yield 100 distinct objects
        # (strong refs retained above, so identity comparison is sound).
        assert all(r is wild0_recs[0] for r in wild0_recs), \
            "records were copied per publish"

        # opt-in record-mutator tier: declared hooks get isolation
        mut_recs: list = []            # strong refs: id() stays valid

        class MutateWild0(Hook):
            id = "mutate-wild0"
            select_subscribers_mutates_records = True

            def on_select_subscribers(self, subscribers, packet):
                rec = subscribers.subscriptions.get("wild-0")
                if rec is not None:
                    mut_recs.append(rec)
                    rec.qos = 2            # must not leak to the caches
                return subscribers

        broker.add_hook(MutateWild0())
        for i in range(3):
            await p.publish("scale/x1/t", b"m2", qos=0)
            await s.next_message(timeout=10)
        assert len(mut_recs) == 3
        assert len({id(r) for r in mut_recs}) == 3, \
            "mutator saw a shared record"
        stored = broker.topics.subscribers("scale/x1/t")
        assert stored.subscriptions["wild-0"].qos == 0, \
            "record mutation leaked into the index"
