"""ADR 008 small-corpus auto-routing: tiny corpora serve from the CPU
trie; growing past ROUTE_SUBS_MAX flips to the device path — with
exact results either side of the flip."""


from maxmq_tpu.matching import TopicIndex
from maxmq_tpu.matching.sig import SigEngine
from maxmq_tpu.protocol import Subscription

from test_nfa_parity import normalize


def _as_set(r):
    to_set = getattr(r, "to_set", None)
    return to_set() if to_set is not None else r


def test_exact_corpus_above_threshold_takes_device_path():
    """A large exact-only corpus stays on the device path: with warmed
    buckets the device beats the trie even without wildcards (ADR 008);
    link-degraded regimes are the batcher bypass's job, not a static
    rule."""
    idx = TopicIndex()
    for i in range(2000):                  # > ROUTE_SUBS_MAX
        idx.subscribe(f"c{i}", Subscription(filter=f"ex/{i}/t", qos=1))
    eng = SigEngine(idx)
    got = eng.subscribers_fixed_batch(["ex/7/t", "ex/1999/t", "nope"])
    assert eng.trie_routed == 0
    assert "c7" in _as_set(got[0]).subscriptions
    assert "c1999" in _as_set(got[1]).subscriptions
    assert len(_as_set(got[2]).subscriptions) == 0


def test_tiny_mixed_corpus_routes_to_trie():
    idx = TopicIndex()
    for i in range(100):                   # <= ROUTE_SUBS_MAX
        idx.subscribe(f"c{i}", Subscription(filter=f"m/{i}/+", qos=0))
    eng = SigEngine(idx)
    got = eng.subscribers_fixed_batch(["m/3/x"])
    assert eng.trie_routed == 1
    assert "c3" in got[0].subscriptions


def test_crossing_threshold_flips_to_device():
    """Corpus growth past ROUTE_SUBS_MAX must engage the device path,
    with parity across the flip."""
    idx = TopicIndex()
    for i in range(SigEngine.ROUTE_SUBS_MAX - 10):
        idx.subscribe(f"e{i}", Subscription(filter=f"fl/{i}/t", qos=1))
    eng = SigEngine(idx)
    topics = ["fl/5/t", "fl/42/t"]
    eng.subscribers_fixed_batch(topics)
    assert eng.trie_routed == 2            # tiny: trie

    for i in range(40):                    # cross the threshold
        idx.subscribe(f"w{i}", Subscription(filter=f"fl/{i}/+", qos=0))
    eng.refresh(force=True)
    assert not eng._routes_to_trie()
    before = eng.trie_routed
    got2 = eng.subscribers_fixed_batch(topics)
    assert eng.trie_routed == before, "device path should have served"
    for t, r in zip(topics, got2):
        assert normalize(_as_set(r)) == normalize(idx.subscribers(t)), t


def test_route_small_off_restores_device_path():
    idx = TopicIndex()
    idx.subscribe("c1", Subscription(filter="a/b", qos=1))
    eng = SigEngine(idx)
    eng.route_small = False
    got = eng.subscribers_fixed_batch(["a/b"])
    assert eng.trie_routed == 0
    assert "c1" in _as_set(got[0]).subscriptions


async def test_batcher_honors_routing():
    """The batcher's pipelined split path must not force a device round
    trip for a corpus the router claims."""
    from maxmq_tpu.matching.batcher import MicroBatcher

    idx = TopicIndex()
    for i in range(50):
        idx.subscribe(f"c{i}", Subscription(filter=f"rb/{i}", qos=0))
    eng = SigEngine(idx)
    mb = MicroBatcher(eng, window_us=0, pipeline_depth=3)
    try:
        r = await mb.subscribers_async("rb/9")
        assert "c9" in r.subscriptions
        assert eng.trie_routed >= 1
    finally:
        await mb.close()
