"""ADR 022: WAN link shaping + RTT-adaptive liveness + the geoday
harness smoke.

The ShapeSpec's arithmetic is pure integer-ns over a caller-supplied
clock with a private seeded PRNG, so the math tests here are exact
replays — no sleeps, no tolerance bands. The cluster-level tests then
prove the three shape sites behave on a live mesh: a shaped link is a
slow FIFO pipe (reorder-free), its blip audit never fires a false
resync, and the RTT-adaptive deadlines keep a 150ms link alive on the
same mesh where a genuinely dead node still flaps. The rehome test is
the ADR-021 dead-owner-blackhole regression: QoS1 forwards parked
against a killed owner must follow the session's epoch-fenced
takeover to the surviving winner.
"""

import asyncio
import time
from contextlib import asynccontextmanager

import pytest

from harness import GeoDay
from maxmq_tpu import faults
from maxmq_tpu.broker import (Broker, BrokerOptions, Capabilities,
                              TCPListener)
from maxmq_tpu.cluster import ClusterManager, PeerSpec
from maxmq_tpu.faults import ShapeSpec
from maxmq_tpu.hooks import AllowHook
from maxmq_tpu.mqtt_client import MQTTClient


@pytest.fixture(autouse=True)
def clean_faults():
    faults.clear()
    yield
    faults.clear()


# ----------------------------------------------------------------------
# ShapeSpec math (scripted clock, exact)
# ----------------------------------------------------------------------


def test_shape_delay_is_exact_and_fifo():
    s = ShapeSpec(delay_ms=30.0)
    assert s.depart_ns(1_000, 100) == 1_000 + 30_000_000
    # FIFO fence: a later call can never be scheduled earlier
    first = s.depart_ns(2_000, 100)
    assert s.depart_ns(2_000, 100) >= first
    assert s.deferrals == 3


def test_shape_jitter_bounded_seeded_and_reorder_free():
    a = ShapeSpec(delay_ms=10.0, jitter_ms=5.0, seed=42)
    b = ShapeSpec(delay_ms=10.0, jitter_ms=5.0, seed=42)
    last = 0
    for i in range(200):
        now = i * 1_000_000
        da = a.depart_ns(now, 64)
        # same seed -> bit-identical schedule
        assert da == b.depart_ns(now, 64)
        # within [delay, delay+jitter] unless the FIFO fence clamps up
        assert da >= max(now + 10_000_000, last)
        assert da <= max(now + 15_000_000, last)
        last = da
    # distinct seeds diverge (the per-direction CRC seeding matters)
    c = ShapeSpec(delay_ms=10.0, jitter_ms=5.0, seed=43)
    assert any(c.depart_ns(i * 1_000_000, 64)
               != b.depart_ns(i * 1_000_000, 64) for i in range(20))


def test_shape_token_bucket_paces_to_rate():
    # 1 Mbit/s = 125000 bytes/s; burst 10_000 bytes passes at line rate
    s = ShapeSpec(rate_bps=1_000_000, burst_bytes=10_000)
    assert s.depart_ns(0, 10_000) == 0          # burst: no wait
    # next 125000 bytes owe exactly one second of debt
    t = s.depart_ns(0, 125_000)
    assert t == pytest.approx(1e9, rel=1e-6)
    # after the debt drains (clock advances 1s + refill time), a small
    # item passes again without waiting beyond the fence
    t2 = s.depart_ns(int(2.1e9), 100)
    assert t2 == pytest.approx(2.1e9, rel=1e-3)


def test_shape_loss_deterministic_and_counted():
    a = ShapeSpec(loss=0.3, seed=7)
    b = ShapeSpec(loss=0.3, seed=7)
    draws = [a.lose() for _ in range(500)]
    assert draws == [b.lose() for _ in range(500)]
    assert a.losses == sum(draws)
    assert 0 < sum(draws) < 500         # neither all nor nothing
    none = ShapeSpec(loss=0.0, seed=7)
    assert not any(none.lose() for _ in range(100))
    assert none.losses == 0


def test_shape_helpers_and_validation():
    spec = faults.shape("a", "b", delay_ms=5.0, loss=0.1)
    assert faults.get_shape(faults.partition_key("a", "b")) is spec
    assert faults.REGISTRY.any_shaped()
    # per-direction CRC seeds differ -> independent streams
    back = faults.shape("b", "a", delay_ms=5.0, loss=0.1)
    assert back._rng != spec._rng
    faults.unshape("a", "b")
    assert faults.get_shape("a->b") is None
    assert faults.get_shape("b->a") is None
    assert not faults.REGISTRY.any_shaped()
    with pytest.raises(ValueError):
        ShapeSpec(loss=1.5)
    with pytest.raises(ValueError):
        ShapeSpec(delay_ms=-1.0)


# ----------------------------------------------------------------------
# Live-mesh fixtures (mirrors tests/test_partition.py)
# ----------------------------------------------------------------------


async def make_node() -> Broker:
    b = Broker(BrokerOptions(capabilities=Capabilities(
        sys_topic_interval=0)))
    b.add_hook(AllowHook())
    listener = b.add_listener(TCPListener("t", "127.0.0.1:0"))
    await b.serve()
    b.test_port = listener._server.sockets[0].getsockname()[1]
    return b


@asynccontextmanager
async def cluster(topology: dict[str, list[str]], **kw):
    brokers: dict[str, Broker] = {}
    managers: dict[str, ClusterManager] = {}
    for name in topology:
        brokers[name] = await make_node()
    kw.setdefault("keepalive", 0.25)
    kw.setdefault("backoff_initial_s", 0.05)
    kw.setdefault("backoff_max_s", 0.3)
    kw.setdefault("session_sync", "always")
    kw.setdefault("session_sync_timeout_ms", 400)
    kw.setdefault("session_takeover_timeout_ms", 400)
    for name, peers in topology.items():
        specs = [PeerSpec(p, "127.0.0.1", brokers[p].test_port)
                 for p in peers]
        mgr = ClusterManager(brokers[name], name, specs, **kw)
        brokers[name].attach_cluster(mgr)
        managers[name] = mgr
        await mgr.start()
    try:
        yield brokers, managers
    finally:
        for b in brokers.values():
            await b.close()


async def wait_for(predicate, timeout: float = 10.0, what: str = ""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        await asyncio.sleep(0.02)
    raise AssertionError(f"condition not reached in {timeout}s: {what}")


async def connect(broker: Broker, client_id: str, **kw) -> MQTTClient:
    c = MQTTClient(client_id=client_id, **kw)
    await c.connect("127.0.0.1", broker.test_port)
    return c


PAIR = {"A": ["B"], "B": ["A"]}
MESH = {"A": ["B", "C"], "B": ["A", "C"], "C": ["A", "B"]}


# ----------------------------------------------------------------------
# Shaped data path on a live pair
# ----------------------------------------------------------------------


async def test_shaped_link_delivers_in_order_with_no_loss():
    """Delay + jitter + rate on A->B: the deferral queue must hold
    every QoS1 forward to its departure stamp WITHOUT reordering (a
    shaped link is a slow pipe, not a shuffler) and without losing a
    single PUBACKed message."""
    async with cluster(PAIR) as (brokers, mgrs):
        await wait_for(lambda: mgrs["A"].links_up == 1
                       and mgrs["B"].links_up == 1)
        sub = await connect(brokers["B"], "wan-sub")
        await sub.subscribe(("wan/seq/#", 1))
        await wait_for(lambda: bool(
            mgrs["A"].routes.nodes_for("wan/seq/x")))
        # jitter 2x the gap between publishes: unshaped, this WOULD
        # reorder; the FIFO fence must prevent it
        faults.shape("A", "B", delay_ms=20.0, jitter_ms=40.0,
                     rate_bps=2_000_000)
        pub = await connect(brokers["A"], "wan-pub")
        n = 30
        for i in range(n):
            await pub.publish(f"wan/seq/{i % 3}", b"%03d" % i, qos=1)
        got = []
        deadline = time.monotonic() + 15.0
        while len(got) < n and time.monotonic() < deadline:
            try:
                msg = await sub.next_message(timeout=1.0)
            except asyncio.TimeoutError:
                continue
            got.append(int(msg.payload))
        assert got == list(range(n)), f"loss or reorder: {got}"
        link = mgrs["A"].links["B"]
        assert link.shape_deferrals > 0, "shape never deferred"
        spec = faults.get_shape("A->B")
        assert spec is not None and spec.deferrals > 0
        await pub.close()
        await sub.close()


async def test_shaped_link_blip_audit_no_false_resyncs():
    """A lossless shaped link slows every hb item down uniformly; the
    RTT-aware blip debounce must keep the ADR-020 audit from reading
    that lag as loss — zero resyncs, zero flaps, zero loss."""
    async with cluster(PAIR) as (brokers, mgrs):
        await wait_for(lambda: mgrs["A"].links_up == 1
                       and mgrs["B"].links_up == 1)
        sub = await connect(brokers["B"], "audit-sub")
        await sub.subscribe(("wan/audit/#", 1))
        await wait_for(lambda: bool(
            mgrs["A"].routes.nodes_for("wan/audit/x")))
        faults.shape("A", "B", delay_ms=40.0, jitter_ms=5.0)
        faults.shape("B", "A", delay_ms=40.0, jitter_ms=5.0)
        flaps0 = mgrs["A"].link_flaps + mgrs["B"].link_flaps
        pub = await connect(brokers["A"], "audit-pub")
        got = set()
        for i in range(12):
            payload = b"audit-%d" % i
            await pub.publish("wan/audit/t", payload, qos=1)
            await asyncio.sleep(0.08)   # spread across keepalives
        deadline = time.monotonic() + 10.0
        while len(got) < 12 and time.monotonic() < deadline:
            try:
                got.add(bytes((await sub.next_message(
                    timeout=1.0)).payload))
            except asyncio.TimeoutError:
                pass
        assert len(got) == 12
        assert mgrs["A"].blip_resyncs == 0
        assert mgrs["B"].blip_resyncs == 0
        assert mgrs["A"].link_flaps + mgrs["B"].link_flaps == flaps0
        await pub.close()
        await sub.close()


async def test_rtt_adaptive_deadline_keeps_slow_link_alive():
    """The crux of ADR 022's liveness half: with the ping budget
    floored at 100ms, a 150ms-RTT link survives ONLY because the
    deadline stretches by k x measured RTT — and on the same mesh a
    genuinely dead node still flaps. Zeroing k makes the slow link
    flap too, proving the extension (not luck) carried it."""
    async with cluster(MESH, keepalive=0.3,
                       rtt_deadline_k=4.0) as (brokers, mgrs):
        await wait_for(lambda: all(m.links_up == 2
                                   for m in mgrs.values()))
        faults.shape("A", "B", delay_ms=75.0)
        faults.shape("B", "A", delay_ms=75.0)
        # seed the EWMA as if the ADR-017 probes already measured the
        # link (deterministic; the probes would converge there anyway)
        for name, peer in (("A", "B"), ("B", "A")):
            st = mgrs[name].membership.peers[peer]
            st.rtt_ns = 0.15e9
            st.skew_samples = 1
            mgrs[name].links[peer].connect_timeout = 0.1
        assert mgrs["A"].link_deadline("B", 0.1) >= 0.7
        assert mgrs["A"].rtt_adaptive_extended > 0
        flaps0 = (mgrs["A"].membership.peers["B"].flaps,
                  mgrs["B"].membership.peers["A"].flaps)
        # a genuinely dead node must still flap under shaping
        dead0 = mgrs["A"].membership.peers["C"].flaps
        await brokers["C"].close()
        await wait_for(
            lambda: mgrs["A"].membership.peers["C"].flaps > dead0,
            timeout=15.0, what="dead node flapped")
        # ... several keepalive periods later the slow link is intact
        await asyncio.sleep(1.2)
        assert mgrs["A"].membership.peers["B"].flaps == flaps0[0]
        assert mgrs["B"].membership.peers["A"].flaps == flaps0[1]
        assert mgrs["A"].links["B"].connected
        # k=0: the floor alone (100ms) cannot absorb a 150ms RTT
        mgrs["A"].rtt_deadline_k = 0.0
        await wait_for(
            lambda: mgrs["A"].membership.peers["B"].flaps > flaps0[0],
            timeout=15.0, what="k=0 flapped the slow link")


async def test_kill_during_park_rehomes_forwards_to_takeover_winner():
    """ADR-021 blackhole regression: kill the owner while QoS1
    forwards sit parked against its link, then reconnect the session
    at a survivor. The epoch-fenced takeover must pull the parked
    copies over to the winner's link — no heal, no expiry, no loss."""
    async with cluster(MESH, fwd_durability="chained") \
            as (brokers, mgrs):
        await wait_for(lambda: all(m.links_up == 2
                                   for m in mgrs.values()))
        sess = await connect(brokers["C"], "park-sess", version=5,
                             clean_start=False, session_expiry=3600)
        await sess.subscribe(("wan/park/#", 1))
        await wait_for(lambda: bool(
            mgrs["A"].routes.nodes_for("wan/park/x"))
            and "park-sess" in mgrs["A"].sessions.ledger
            and "park-sess" in mgrs["B"].sessions.ledger,
            what="session replicated")
        await sess.disconnect()
        # owner C dies; A's forwards for wan/park/# park on the C link
        await brokers["C"].close()
        await wait_for(lambda: not mgrs["A"].links["C"].connected
                       and not mgrs["B"].links["C"].connected)
        pub = await connect(brokers["A"], "park-pub")
        sent = set()
        for i in range(8):
            payload = b"park-%d" % i
            await pub.publish(f"wan/park/{i % 2}", payload, qos=1)
            sent.add(payload)
        await wait_for(lambda: mgrs["A"].fwd_parked_now > 0,
                       what="forwards parked against dead owner")
        # the client re-attaches at survivor B: takeover + rehome
        sess_b = await connect(brokers["B"], "park-sess", version=5,
                               clean_start=False, session_expiry=3600)
        assert sess_b.session_present
        got = set()
        deadline = time.monotonic() + 15.0
        while not sent <= got and time.monotonic() < deadline:
            try:
                got.add(bytes((await sess_b.next_message(
                    timeout=1.0)).payload))
            except asyncio.TimeoutError:
                pass
        assert sent <= got, f"blackholed: {sent - got}"
        assert mgrs["A"].fwd_parked_rehomed > 0
        # the moved copies left the dead link's parked set
        assert not any(b"park-" in p for _t, p, _k
                       in mgrs["A"].links["C"].parked)
        await pub.close()
        await sess_b.close()

test_kill_during_park_rehomes_forwards_to_takeover_winner\
    ._async_timeout = 60


# ----------------------------------------------------------------------
# GeoDay smoke (compressed RTTs; also runs in the asyncio-debug lane)
# ----------------------------------------------------------------------


async def test_geoday_smoke_slo_sheet_passes():
    day = GeoDay(rtt_scale=0.1, fanin_msgs=6, share_msgs=6,
                 outage_msgs=8, roam_msgs=6, keepalive=0.5,
                 will_grace=0.5, sync_timeout_ms=600, settle_s=12.0)
    sheet = await day.run()
    assert sheet["pass"], f"SLO violations: {sheet['violations']}"
    assert sheet["pubacked_loss"] == 0
    assert sheet["pubacked_total"] > 0
    assert sheet["wills_fired"] == 1
    assert sheet["wills_delivered"] == 1
    assert sheet["false_link_flaps"] == 0
    assert sheet["share_duplicates"] == 0
    assert sheet["outage_session_present"]
    assert sheet["takeover_session_present"]
    assert sheet["fwd_parked_rehomed"] > 0
    assert sheet["shape_deferrals"] > 0
    assert sheet["rtt_adaptive_extended"] > 0
    assert 0 <= sheet["heal_convergence_ms"] <= sheet["heal_budget_ms"]
    assert 0 <= sheet["outage_takeover_recovery_ms"] \
        <= sheet["takeover_budget_ms"]
    names = [p["name"] for p in sheet["phases"]]
    assert names == ["shape_links", "regional_fanin",
                     "cross_region_share", "region_outage_heal",
                     "roam_takeover"]
    # shapes armed, recorded for replay, and cleared afterwards
    assert sheet["phases"][0]["armed_sites"]
    assert not faults.REGISTRY.any_shaped()
    assert not faults.REGISTRY.any_armed()

test_geoday_smoke_slo_sheet_passes._async_timeout = 120
