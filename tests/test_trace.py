"""ADR-015 publish-path tracing suite: histogram bucket math + text
exposition, deterministic sampling (incl. the zero-allocations-when-off
contract), flight-recorder ring bounds and slow-threshold capture,
Chrome trace_event export, span nesting across the event loop / writer
thread / writer task / bridge boundaries on a real broker, the
per-stage error counter, and the Prometheus conformance checker the CI
lane runs (imported and exercised directly, so the tool is under test).
"""

import asyncio
import importlib.util
import json
import os
import time
import urllib.request

import pytest

from test_broker_system import connect, running_broker

from maxmq_tpu import faults
from maxmq_tpu.broker import Broker, BrokerOptions, Capabilities, TCPListener
from maxmq_tpu.hooks import AllowHook
from maxmq_tpu.hooks.journal import WriteBehindStore
from maxmq_tpu.hooks.storage import MemoryStore, StorageHook
from maxmq_tpu.metrics import (Histogram, MetricsServer, Registry,
                               register_broker_metrics)
from maxmq_tpu.trace import (CRITICAL_STAGES, MAX_DRAIN_SPANS,
                             PipelineTracer, STAGES)


@pytest.fixture(autouse=True)
def clean_faults():
    faults.clear()
    yield
    faults.clear()
    faults.REGISTRY.reset_clock()


async def poll(predicate, timeout: float = 5.0, what: str = ""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        await asyncio.sleep(0.02)
    raise AssertionError(f"condition not reached in {timeout}s: {what}")


def _checker():
    """Import scripts/check_metrics_exposition.py as a module (scripts/
    is not a package) so its validator is directly under test."""
    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "scripts", "check_metrics_exposition.py")
    spec = importlib.util.spec_from_file_location("_expo_check", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- histogram units ---------------------------------------------------


def test_histogram_bucket_math():
    h = Histogram(buckets=(0.001, 0.01, 0.1))
    for v in (0.0005, 0.001, 0.05, 5.0):
        h.observe(v)
    # per-bucket: le=0.001 takes 0.0005 AND the exact-bound 0.001
    assert h.counts == [2, 0, 1, 1]
    assert h.count == 4
    assert h.sum == pytest.approx(5.0515)
    # quantiles interpolate within the owning bucket; the overflow
    # bucket clamps to the last finite bound
    assert 0.0 < h.quantile(0.25) <= 0.001
    assert 0.01 < h.quantile(0.74) <= 0.1
    assert h.quantile(0.99) == 0.1


def test_histogram_exposition_format():
    reg = Registry()
    h = Histogram(buckets=(0.001, 0.01))
    for v in (0.0005, 0.005, 2.0):
        h.observe(v)
    reg.histogram_func("t_seconds", "help.",
                       lambda: [({"stage": "x"}, h)])
    text = reg.expose()
    assert "# TYPE t_seconds histogram" in text
    assert 't_seconds_bucket{stage="x",le="0.001"} 1' in text
    assert 't_seconds_bucket{stage="x",le="0.01"} 2' in text
    assert 't_seconds_bucket{stage="x",le="+Inf"} 3' in text
    assert 't_seconds_count{stage="x"} 3' in text
    assert 't_seconds_sum{stage="x"} 2.0055' in text


# -- tracer units ------------------------------------------------------


def _finished_trace(tracer, e2e_ns=1_000_000, topic="t/x", qos=0):
    tr = tracer.sample(topic, qos, "c")
    assert tr is not None
    tr.span("admission", tr.start_ns, tr.start_ns + e2e_ns // 2)
    tr.span("fanout", tr.start_ns + e2e_ns // 2, tr.start_ns + e2e_ns)
    tracer.finish(tr, end_ns=tr.start_ns + e2e_ns)
    return tr


def test_sampling_stride_and_zero_alloc_counter():
    tracer = PipelineTracer(sample_n=2)
    got = [tracer.sample("t", 0, "c") for _ in range(10)]
    assert sum(1 for tr in got if tr is not None) == 5
    assert tracer.allocations == 5
    off = PipelineTracer(sample_n=0)
    assert all(off.sample("t", 0, "c") is None for _ in range(10))
    assert off.allocations == 0 and off.sampled == 0


def test_flight_recorder_ring_bounds():
    tracer = PipelineTracer(sample_n=1, ring=4)
    for _ in range(10):
        _finished_trace(tracer)
    assert tracer.ring_depth == 4
    ids = [e["id"] for e in tracer.report()["entries"]]
    assert ids == [7, 8, 9, 10]          # recency ring, oldest first


def test_slow_threshold_capture_and_slowest_list():
    tracer = PipelineTracer(sample_n=1, slow_ms=10.0, ring=8)
    _finished_trace(tracer, e2e_ns=5_000_000)       # 5ms: under
    assert tracer.ring_depth == 0 and tracer.slow_captured == 0
    _finished_trace(tracer, e2e_ns=20_000_000)      # 20ms: captured
    assert tracer.ring_depth == 1 and tracer.slow_captured == 1
    entry = tracer.report()["entries"][0]
    assert entry["slow"] is True
    assert entry["e2e_ms"] == pytest.approx(20.0)
    # the slowest-ever list survives ring churn and stays bounded
    for ms in range(11, 30):
        _finished_trace(tracer, e2e_ns=ms * 1_000_000)
    slowest = tracer.report()["slowest"]
    assert len(slowest) <= 8
    assert slowest[-1]["e2e_ms"] == pytest.approx(29.0)
    assert all(a["e2e_ms"] <= b["e2e_ms"]
               for a, b in zip(slowest, slowest[1:]))


def test_chrome_export_is_valid_trace_event_json():
    tracer = PipelineTracer(sample_n=1)
    _finished_trace(tracer, e2e_ns=3_000_000)
    blob = json.dumps(tracer.chrome_events())
    doc = json.loads(blob)
    events = doc["traceEvents"]
    # ADR 017: process_name metadata rows name the per-node tracks;
    # every span row stays a complete ('X') event
    spans = [e for e in events if e["ph"] != "M"]
    assert spans and all(e["ph"] == "X" for e in spans)
    assert any(e["ph"] == "M" and e["name"] == "process_name"
               for e in events)
    names = {e["name"] for e in spans}
    assert "admission" in names and "fanout" in names
    for e in spans:
        assert isinstance(e["ts"], int) and e["dur"] >= 1


def test_fault_registry_clock_drives_spans():
    """Deterministic-under-test contract: the tracer reads time through
    faults.REGISTRY.clock_ns, so a scripted clock scripts the spans."""
    t = [0]

    def scripted():
        t[0] += 1_000_000               # 1ms per observation
        return t[0]

    faults.REGISTRY.clock_ns = scripted
    tracer = PipelineTracer(sample_n=1)
    tr = tracer.sample("t", 0, "c")     # one clock read
    t0 = tracer.clock()
    tr.span("fanout", t0, tracer.clock())
    tracer.finish(tr)
    entry = tracer.report()["entries"][0]
    span = next(s for s in entry["spans"] if s["stage"] == "fanout")
    assert span["dur_us"] == 1000       # exactly one scripted tick
    assert entry["e2e_ms"] == pytest.approx(3.0)  # 3 ticks start->end


def test_stage_errors_counter_and_exposition():
    tracer = PipelineTracer()           # sampling off: errors still count
    tracer.note_error("drain", "queue_full", 3)
    tracer.note_error("bridge", "refused")
    assert tracer.stage_errors[("drain", "queue_full")] == 3

    class _B:                            # minimal broker facade
        pass

    b = _B()
    b.tracer = tracer
    reg = Registry()
    from maxmq_tpu.metrics import _register_trace_metrics
    _register_trace_metrics(reg, b)
    text = reg.expose()
    assert ('maxmq_broker_stage_errors_total'
            '{stage="drain",reason="queue_full"} 3') in text
    assert ('maxmq_broker_stage_errors_total'
            '{stage="bridge",reason="refused"} 1') in text
    # every pipeline stage exposes its histogram triplet even untouched
    for stage in STAGES:
        assert (f'maxmq_broker_publish_stage_seconds_count'
                f'{{stage="{stage}"}} 0') in text


# -- e2e: spans on a real broker --------------------------------------


async def test_trie_path_spans_and_drain():
    async with running_broker(trace_sample_n=1) as broker:
        sub = await connect(broker, "s1")
        await sub.subscribe("t/#")
        pub = await connect(broker, "p1")
        await pub.publish("t/x", b"payload")
        await sub.next_message(timeout=3)
        await poll(lambda: broker.tracer.ring_depth > 0, what="trace")
        entry = broker.tracer.report()["entries"][0]
        stages = {s["stage"] for s in entry["spans"]}
        assert {"decode", "admission", "match_device",
                "fanout"} <= stages
        assert entry["qos"] == 0 and entry["topic"] == "t/x"
        assert entry["client"] == "p1"
        # drain span lands after finish, from the writer task, and is
        # appended to the live flight-recorder entry
        await poll(lambda: entry["drains"], what="drain span")
        assert entry["drains"][0]["client"] == "s1"
        # zero stage errors on a healthy publish
        assert broker.tracer.stage_errors == {}
        await pub.disconnect()
        await sub.disconnect()


async def test_zero_allocations_when_off():
    async with running_broker() as broker:      # default: tracing off
        sub = await connect(broker, "s1")
        await sub.subscribe("t/#")
        pub = await connect(broker, "p1")
        for i in range(10):
            await pub.publish("t/x", b"m", qos=1)
        await sub.next_message(timeout=3)
        assert broker.tracer.allocations == 0
        assert broker.tracer.sampled == 0
        assert broker.tracer.ring_depth == 0
        await pub.disconnect()
        await sub.disconnect()


async def test_durable_barrier_span_crosses_writer_thread():
    """storage_sync=always: the barrier span opens on the loop and is
    closed by an ack released from the storage writer thread; a slow
    group commit (hang fault in the WRITER thread) must show up as
    barrier time, and the critical-path spans must sum to ~e2e (the
    acceptance bar: within 10%)."""
    store = WriteBehindStore(MemoryStore(), policy="always")
    b = Broker(BrokerOptions(capabilities=Capabilities(
        sys_topic_interval=0, trace_sample_n=1, trace_slow_ms=20.0)))
    b.add_hook(AllowHook())
    b.add_hook(StorageHook(store))
    lst = b.add_listener(TCPListener("t", "127.0.0.1:0"))
    await b.serve()
    b.test_port = lst._server.sockets[0].getsockname()[1]
    try:
        sub = await connect(b, "s1")
        await sub.subscribe(("t/#", 1))
        pub = await connect(b, "p1")
        # fast publish first: under the 20ms slow threshold -> NOT
        # flight-recorded (but histograms still fed)
        await pub.publish("t/fast", b"m", qos=1, timeout=5)
        await poll(lambda: b.tracer.sampled >= 1, what="sampled")
        assert b.tracer.ring_depth == 0
        # slow publish: the commit covering its barrier hangs 60ms in
        # the writer thread
        faults.arm(faults.STORAGE_COMMIT, "hang", count=1, delay_s=0.06)
        t0 = time.perf_counter()
        await pub.publish("t/slow", b"m", qos=1, timeout=10)
        measured_ms = (time.perf_counter() - t0) * 1e3
        await poll(lambda: b.tracer.ring_depth > 0, what="slow capture")
        entry = b.tracer.report()["entries"][0]
        assert entry["slow"] is True and entry["topic"] == "t/slow"
        spans = {s["stage"]: s for s in entry["spans"]}
        assert "barrier" in spans and "ack" in spans
        assert spans["barrier"]["dur_us"] >= 50_000
        # spans are the decomposition of the measured e2e: within 10%
        assert entry["critical_sum_ms"] >= 0.9 * entry["e2e_ms"]
        assert entry["e2e_ms"] <= measured_ms * 1.1
        assert b.storage_barrier_waits >= 1
        # journal_commit histogram fed from the writer thread
        assert b.tracer.stage_hist["journal_commit"].count >= 1
        await pub.disconnect()
        await sub.disconnect()
    finally:
        await b.close()


async def test_matcher_pipeline_split_spans_through_supervisor():
    """Matcher mode: the batcher stamps dispatch/done marks, the
    ADR-011 supervisor forwards them, and the trace splits the matcher
    leg into match_queue + match_device (+ pipeline_wait)."""
    from maxmq_tpu.matching.batcher import MicroBatcher
    from maxmq_tpu.matching.supervisor import SupervisedMatcher

    class _TrieEngine:
        def __init__(self, index):
            self.index = index

        def subscribers_batch(self, topics):
            return [self.index.subscribers(t) for t in topics]

        def refresh(self, force=False):
            return False

    async with running_broker(trace_sample_n=1) as broker:
        batcher = MicroBatcher(_TrieEngine(broker.topics),
                               cpu_bypass=False, window_us=1000)
        batcher.tracer = broker.tracer
        broker.attach_matcher(SupervisedMatcher(
            batcher, index=broker.topics, deadline_ms=2000))
        try:
            sub = await connect(broker, "s1")
            await sub.subscribe("m/#")
            pub = await connect(broker, "p1")
            await pub.publish("m/x", b"payload")
            await sub.next_message(timeout=3)
            await poll(lambda: broker.tracer.ring_depth > 0,
                       what="matcher trace")
            entry = broker.tracer.report()["entries"][0]
            stages = {s["stage"] for s in entry["spans"]}
            assert "match_queue" in stages and "match_device" in stages
            assert entry["degraded"] == ""      # healthy supervisor
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await batcher.close()


async def test_bridge_span_and_link_down_stage_error():
    """Cluster attached: the bridge span wraps the route consult +
    forward enqueue, and a forward whose target link is down lands on
    the stage-error counter as (bridge, link_down)."""
    from maxmq_tpu.cluster import ClusterManager, PeerSpec

    async with running_broker(trace_sample_n=1) as broker:
        mgr = ClusterManager(
            broker, "A", [PeerSpec("B", "127.0.0.1", 1)])
        broker.attach_cluster(mgr)      # attached post-serve: links idle
        # B advertises a route so maybe_forward targets its dead link
        mgr.routes.apply_snapshot("B", 1, 1, {"t/#"})
        sub = await connect(broker, "s1")
        await sub.subscribe("t/#")
        pub = await connect(broker, "p1")
        await pub.publish("t/x", b"payload")
        await sub.next_message(timeout=3)
        await poll(lambda: broker.tracer.ring_depth > 0, what="trace")
        entry = broker.tracer.report()["entries"][0]
        assert "bridge" in {s["stage"] for s in entry["spans"]}
        assert broker.tracer.stage_errors.get(
            ("bridge", "link_down"), 0) >= 1
        assert mgr.forwards_skipped_down >= 1
        await pub.disconnect()
        await sub.disconnect()


async def test_drain_stage_error_from_write_path_drop():
    """The ADR-012 drops_by_reason ledger now surfaces per-stage: a
    queue-refused delivery counts under stage=drain with its reason."""
    async with running_broker(maximum_client_writes_pending=1) as broker:
        sub = await connect(broker, "s1")
        await sub.subscribe("t/#")
        # stall the subscriber's writer so its 1-slot queue wedges
        faults.arm(f"{faults.CLIENT_WRITE}#s1", "hang",
                   count=-1, delay_s=30.0)
        pub = await connect(broker, "p1")
        for i in range(20):
            await pub.publish("t/x", b"m" * 64)
        await poll(lambda: any(s == "drain" for (s, _r)
                               in broker.tracer.stage_errors),
                   what="drain stage error")
        reasons = {r for (s, r) in broker.tracer.stage_errors
                   if s == "drain"}
        assert "queue_full" in reasons
        await pub.disconnect()


async def test_sys_trace_subtree_and_metrics_endpoints():
    async with running_broker(trace_sample_n=1) as broker:
        sub = await connect(broker, "s1")
        await sub.subscribe("t/#")
        pub = await connect(broker, "p1")
        await pub.publish("t/x", b"m", qos=1)
        await sub.next_message(timeout=3)
        await poll(lambda: broker.tracer.ring_depth > 0, what="trace")
        broker.publish_sys_topics()
        assert broker.topics.retained_get(
            "$SYS/broker/trace/sampled") is not None
        assert broker.topics.retained_get(
            "$SYS/broker/trace/ring_depth") is not None
        # sampling off -> the next tick CLEARS the retained subtree
        # (stale values must not masquerade as live ones)
        broker.tracer.sample_n = 0
        broker.publish_sys_topics()
        assert broker.topics.retained_get(
            "$SYS/broker/trace/sampled") is None
        assert broker.topics.retained_get(
            "$SYS/broker/trace/ring_depth") is None
        broker.tracer.sample_n = 1

        reg = Registry()
        register_broker_metrics(reg, broker)
        srv = MetricsServer("127.0.0.1:0", reg, tracer=broker.tracer)
        srv.start()
        try:
            def get(path):
                url = f"http://127.0.0.1:{srv.bound_port}{path}"
                with urllib.request.urlopen(url, timeout=5) as r:
                    return r.read().decode()

            loop = asyncio.get_running_loop()
            traces = json.loads(
                await loop.run_in_executor(None, get, "/traces"))
            assert traces["sample_n"] == 1 and traces["entries"]
            chrome = json.loads(
                await loop.run_in_executor(None, get, "/traces/chrome"))
            assert chrome["traceEvents"]
            page = await loop.run_in_executor(None, get, "/metrics")
            assert "maxmq_broker_publish_e2e_seconds_bucket" in page
        finally:
            srv.stop()
        await pub.disconnect()
        await sub.disconnect()


# -- the conformance checker itself ------------------------------------


def test_exposition_checker_passes_on_real_registry():
    checker = _checker()
    b = Broker(BrokerOptions(capabilities=Capabilities(
        sys_topic_interval=0, trace_sample_n=1)))
    b.add_hook(StorageHook(WriteBehindStore(MemoryStore())))
    b.tracer.observe("fanout", 0.003)
    tr = b.tracer.sample("t", 0, 'cli"ent\\x')
    b.tracer.finish(tr, end_ns=tr.start_ns + 1000)
    reg = Registry()
    register_broker_metrics(reg, b)
    errors = checker.validate(reg.expose())
    assert errors == []
    b.hooks.stop_all()


def test_exposition_checker_catches_violations():
    checker = _checker()
    bad = "\n".join((
        "# TYPE h_seconds histogram",
        'h_seconds_bucket{le="0.1"} 5',
        'h_seconds_bucket{le="1"} 3',        # non-monotonic
        'h_seconds_bucket{le="+Inf"} 5',
        "h_seconds_sum 1.0",
        "h_seconds_count 9",                 # != +Inf bucket
        "no_type_metric 1",                  # no TYPE declared
        'lbl{bad name="x"} 1',               # malformed label
        "dup 1",
    ))
    errors = checker.validate("# TYPE dup counter\n# TYPE lbl gauge\n"
                              "# TYPE no_type_metric_ignored gauge\n"
                              + bad + "\ndup 1\n")
    text = "\n".join(errors)
    assert "non-monotonic" in text
    assert "_count" in text
    assert "no TYPE declared" in text
    assert "malformed" in text or "unparseable" in text
    assert "duplicate series" in text


async def test_drain_watchers_settle_only_when_their_flush_lands():
    """A watcher registered while a flush is in flight must NOT be
    settled by that flush (its packet is still queued) — settling is
    gated on the writer having dequeued past the watcher's enqueue
    seq, so slow-consumer drain latency is reported, not hidden."""
    async with running_broker(trace_sample_n=1) as broker:
        sub = await connect(broker, "s1")
        await sub.subscribe("t/#")
        client = broker.clients.get("s1")
        tracer = broker.tracer
        tr1 = tracer.sample("t/a", 0, "p")
        tr2 = tracer.sample("t/b", 0, "p")
        # watcher 1 at the current dequeue frontier, watcher 2 beyond
        flushed_now = client.outbound.removed
        client._drain_traces = [(tr1, tracer.clock(), flushed_now),
                                (tr2, tracer.clock(), flushed_now + 5)]
        client._settle_drain_traces(flushed_now)
        assert [seq for _t, _n, seq in client._drain_traces] == \
            [flushed_now + 5]                   # tr2 kept pending
        assert len(tr1.drains) == 1 and tr2.drains == []
        await sub.disconnect()


def test_drain_span_cap():
    tracer = PipelineTracer(sample_n=1)
    tr = tracer.sample("t", 0, "c")
    for i in range(20):
        tracer.drain_span(tr, f"c{i}", 0, 1000)
    # the SERVER-side registration caps at MAX_DRAIN_SPANS; the tracer
    # records whatever was registered — the cap constant is the contract
    assert MAX_DRAIN_SPANS < 20
    assert tracer.stage_hist["drain"].count == 20
    assert CRITICAL_STAGES.isdisjoint({"drain", "journal_commit"})
