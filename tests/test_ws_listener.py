"""MQTT-over-WebSocket listener: RFC 6455 handshake + frame bridging.

Drives the broker's own WS server (broker/listeners.py WSListener) with
a minimal in-test WS client — handshake, masked binary frames, ping and
close — and runs a full MQTT CONNECT/SUBSCRIBE/PUBLISH roundtrip through
it. Parity surface: the reference's gorilla-websocket adapter
(vendor/.../v2/listeners/websocket.go).
"""

import asyncio
import base64
import hashlib
import os
import struct

from maxmq_tpu.broker import Broker, BrokerOptions, Capabilities, WSListener
from maxmq_tpu.hooks import AllowHook
from maxmq_tpu.protocol.codec import FixedHeader, PacketType as PT
from maxmq_tpu.protocol.packets import Packet, parse_stream

_WS_MAGIC = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


class WSClient:
    """Just enough RFC 6455 to drive the listener: client handshake,
    masked binary frames out, unmasked frames in."""

    def __init__(self):
        self.reader = None
        self.writer = None
        self._mqtt = bytearray()
        self._parsed: list[Packet] = []

    async def connect(self, host: str, port: int):
        self.reader, self.writer = await asyncio.open_connection(host, port)
        key = base64.b64encode(os.urandom(16)).decode()
        self.writer.write(
            (f"GET /mqtt HTTP/1.1\r\nHost: {host}:{port}\r\n"
             "Upgrade: websocket\r\nConnection: Upgrade\r\n"
             f"Sec-WebSocket-Key: {key}\r\n"
             "Sec-WebSocket-Version: 13\r\n"
             "Sec-WebSocket-Protocol: mqtt\r\n\r\n").encode())
        await self.writer.drain()
        resp = await asyncio.wait_for(
            self.reader.readuntil(b"\r\n\r\n"), 5)
        assert b"101" in resp.split(b"\r\n", 1)[0]
        want = base64.b64encode(hashlib.sha1(
            (key + _WS_MAGIC).encode()).digest())
        assert want in resp
        return self

    def send_frame(self, opcode: int, payload: bytes):
        mask = os.urandom(4)
        head = bytearray([0x80 | opcode])
        n = len(payload)
        if n < 126:
            head.append(0x80 | n)
        elif n < 65536:
            head.append(0x80 | 126)
            head.extend(struct.pack(">H", n))
        else:
            head.append(0x80 | 127)
            head.extend(struct.pack(">Q", n))
        masked = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        self.writer.write(bytes(head) + mask + masked)

    def send_mqtt(self, packet: Packet):
        self.send_frame(0x2, packet.encode())

    async def recv_frame(self, timeout: float = 5.0):
        hdr = await asyncio.wait_for(self.reader.readexactly(2), timeout)
        opcode = hdr[0] & 0x0F
        length = hdr[1] & 0x7F
        if length == 126:
            length = struct.unpack(
                ">H", await self.reader.readexactly(2))[0]
        elif length == 127:
            length = struct.unpack(
                ">Q", await self.reader.readexactly(8))[0]
        payload = await self.reader.readexactly(length)
        return opcode, payload

    async def recv_mqtt(self, timeout: float = 5.0) -> Packet:
        while True:
            if self._parsed:
                return self._parsed.pop(0)
            self._parsed.extend(
                Packet.decode(fh, body)
                for fh, body in parse_stream(self._mqtt))
            if self._parsed:
                continue
            opcode, payload = await self.recv_frame(timeout)
            if opcode in (0x0, 0x1, 0x2):
                self._mqtt.extend(payload)


async def ws_broker():
    b = Broker(BrokerOptions(capabilities=Capabilities(
        sys_topic_interval=0)))
    b.add_hook(AllowHook())
    lst = b.add_listener(WSListener("ws1", "127.0.0.1:0"))
    await b.serve()
    port = lst._server.sockets[0].getsockname()[1]
    return b, port


async def test_ws_mqtt_roundtrip():
    broker, port = await ws_broker()
    try:
        c = await WSClient().connect("127.0.0.1", port)
        c.send_mqtt(Packet(fixed=FixedHeader(type=PT.CONNECT),
                           protocol_version=4, clean_start=True,
                           client_id="wsc"))
        connack = await c.recv_mqtt()
        assert connack.type == PT.CONNACK and connack.reason_code == 0

        from maxmq_tpu.protocol.packets import Subscription
        c.send_mqtt(Packet(fixed=FixedHeader(type=PT.SUBSCRIBE),
                           protocol_version=4, packet_id=1,
                           filters=[Subscription(filter="ws/+")]))
        suback = await c.recv_mqtt()
        assert suback.type == PT.SUBACK

        c.send_mqtt(Packet(fixed=FixedHeader(type=PT.PUBLISH),
                           protocol_version=4, topic="ws/x",
                           payload=b"frame-bridged"))
        msg = await c.recv_mqtt()
        assert (msg.type, msg.topic, msg.payload) == \
            (PT.PUBLISH, "ws/x", b"frame-bridged")

        # a split MQTT packet across two WS frames must reassemble
        ping = Packet(fixed=FixedHeader(type=PT.PINGREQ),
                      protocol_version=4).encode()
        c.send_frame(0x2, ping[:1])
        c.send_frame(0x2, ping[1:])
        resp = await c.recv_mqtt()
        assert resp.type == PT.PINGRESP
    finally:
        await broker.close()


async def test_ws_ping_pong_and_close():
    broker, port = await ws_broker()
    try:
        c = await WSClient().connect("127.0.0.1", port)
        c.send_mqtt(Packet(fixed=FixedHeader(type=PT.CONNECT),
                           protocol_version=4, clean_start=True,
                           client_id="wsp"))
        await c.recv_mqtt()
        c.send_frame(0x9, b"hb")            # WS ping
        opcode, payload = await c.recv_frame()
        assert (opcode, payload) == (0xA, b"hb")
        c.send_frame(0x8, b"")              # WS close
        await asyncio.sleep(0.1)
        assert broker.info.clients_connected == 0
    finally:
        await broker.close()


async def test_ws_bad_handshake_rejected():
    broker, port = await ws_broker()
    try:
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(b"GET / HTTP/1.1\r\nHost: x\r\n\r\n")  # no upgrade
        await writer.drain()
        data = await asyncio.wait_for(reader.read(64), 5)
        assert data == b""                  # connection dropped
    finally:
        await broker.close()
