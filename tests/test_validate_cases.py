"""Validate-direction conformance cases from the reference corpus.

The tpackets corpus's ``Invalid*`` entries carry no wire bytes — they
construct packets and expect the reference's XxxValidate step (or the
broker) to reject them (tpackets.go, the cases without RawBytes). This
module ports their SEMANTICS against our validation surface: some live
on ``Packet.validate_*``, some on the broker's processing path, matching
where the reference enforces each rule.
"""

import asyncio

import pytest

from maxmq_tpu.protocol import codes
from maxmq_tpu.protocol.codec import FixedHeader, PacketType as PT
from maxmq_tpu.protocol.codec import MalformedPacketError
from maxmq_tpu.protocol.packets import (Packet, ProtocolError,
                                        Subscription)

from test_broker_system import connect, running_broker


def publish(topic="a/b", qos=0, v5=False, **props) -> Packet:
    p = Packet(fixed=FixedHeader(type=PT.PUBLISH, qos=qos), topic=topic,
               protocol_version=5 if v5 else 4)
    for k, v in props.items():
        setattr(p.properties, k, v)
    return p


# --- TPublishInvalid*: PublishValidate (tpackets.go:2075-2168) ---------

def test_publish_qos_must_have_packet_id():
    # TPublishInvalidQosMustPacketID [MQTT-2.2.1-2]
    p = publish(qos=1)
    p.packet_id = 0
    with pytest.raises(ProtocolError):
        p.validate_publish()


def test_publish_surplus_subscription_identifier():
    # TPublishInvalidSurplusSubID [MQTT-3.3.4-6]
    p = publish(v5=True, subscription_ids=[1])
    with pytest.raises(ProtocolError):
        p.validate_publish()


@pytest.mark.parametrize("topic", ["a/+", "a/#", "+", "#", "a/+/c"])
def test_publish_surplus_wildcard(topic):
    # TPublishInvalidSurplusWildcard(2) [MQTT-3.3.2-2]
    with pytest.raises(ProtocolError):
        publish(topic=topic).validate_publish()


def test_publish_no_topic_no_alias():
    # TPublishInvalidNoTopic [MQTT-3.3.2-1]
    with pytest.raises(ProtocolError):
        publish(topic="").validate_publish()
    # ... but alias-only is legal for v5 [MQTT-3.3.2-6]
    publish(topic="", v5=True, topic_alias=3).validate_publish()


async def test_publish_topic_alias_zero_and_excess():
    # TPublishInvalidTopicAlias / TPublishInvalidExcessTopicAlias
    # [MQTT-3.3.2-8]: enforced where the reference enforces it — on the
    # broker's inbound alias resolution
    async with running_broker(topic_alias_maximum=4) as broker:
        c = await connect(broker, "c1", version=5)
        cl = broker.clients.get("c1")
        assert cl.aliases.resolve_inbound("t", 0) is None       # zero
        assert cl.aliases.resolve_inbound("t", 5) is None       # excess
        assert cl.aliases.resolve_inbound("t", 3) == "t"        # learns
        assert cl.aliases.resolve_inbound("", 3) == "t"         # resolves
        await c.disconnect()


# --- TSubscribeInvalid* / TUnsubscribeInvalid* -------------------------

async def test_subscribe_shared_no_local_rejected():
    # TSubscribeInvalidSharedNoLocal [MQTT-3.8.3-4]: the broker must
    # drop the connection on a $share filter with NoLocal
    async with running_broker() as broker:
        c = await connect(broker, "c1", version=5)
        sub = Packet(fixed=FixedHeader(type=PT.SUBSCRIBE),
                     protocol_version=5, packet_id=7,
                     filters=[Subscription(filter="$share/g/a/b",
                                           no_local=True)])
        c.writer.write(sub.encode())
        await c.writer.drain()
        await c.wait_closed(timeout=5)
        await asyncio.sleep(0.05)
        assert broker.info.clients_connected == 0


def test_subscribe_no_filters_rejected_at_decode():
    # TSubscribeInvalidNoFilters [MQTT-3.8.3-3]: wire twin is the
    # decode-time check
    wire = Packet(fixed=FixedHeader(type=PT.SUBSCRIBE),
                  protocol_version=5, packet_id=8, filters=[]).encode()
    from maxmq_tpu.protocol.packets import parse_stream
    buf = bytearray(wire)
    [(fh, body)] = list(parse_stream(buf))
    with pytest.raises((ProtocolError, MalformedPacketError)):
        Packet.decode(fh, body, 5)


def test_unsubscribe_no_filters_rejected_at_decode():
    # TUnsubscribeInvalidNoFilters [MQTT-3.10.3-2]
    wire = Packet(fixed=FixedHeader(type=PT.UNSUBSCRIBE),
                  protocol_version=5, packet_id=9, filters=[]).encode()
    from maxmq_tpu.protocol.packets import parse_stream
    buf = bytearray(wire)
    [(fh, body)] = list(parse_stream(buf))
    with pytest.raises((ProtocolError, MalformedPacketError)):
        Packet.decode(fh, body, 5)


# --- TDisconnect* encode cases (tpackets.go fail-state section) --------

def test_disconnect_reason_codes_roundtrip():
    # TDisconnectTakeover / ShuttingDown / SecondConnect /
    # ReceiveMaximum: encode-direction cases — the v5 reason code must
    # survive an encode/decode roundtrip
    from maxmq_tpu.protocol.packets import parse_stream
    for code in (codes.ErrSessionTakenOver, codes.ErrServerShuttingDown,
                 codes.ErrProtocolViolationSecondConnect
                 if hasattr(codes, "ErrProtocolViolationSecondConnect")
                 else codes.ErrProtocolViolation,
                 codes.ErrReceiveMaximumExceeded):
        p = Packet(fixed=FixedHeader(type=PT.DISCONNECT),
                   protocol_version=5, reason_code=code.value)
        buf = bytearray(p.encode())
        [(fh, body)] = list(parse_stream(buf))
        got = Packet.decode(fh, body, 5)
        assert got.reason_code == code.value
