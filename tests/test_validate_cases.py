"""Validate-direction conformance cases from the reference corpus.

The tpackets corpus's ``Invalid*`` entries carry no wire bytes — they
construct packets and expect the reference's XxxValidate step (or the
broker) to reject them (tpackets.go, the cases without RawBytes). This
module ports their SEMANTICS against our validation surface: some live
on ``Packet.validate_*``, some on the broker's processing path, matching
where the reference enforces each rule.
"""

import asyncio

import pytest

from maxmq_tpu.protocol import codes
from maxmq_tpu.protocol.codec import FixedHeader, PacketType as PT
from maxmq_tpu.protocol.codec import MalformedPacketError
from maxmq_tpu.protocol.packets import (Packet, ProtocolError,
                                        Subscription)

from test_broker_system import connect, running_broker


def publish(topic="a/b", qos=0, v5=False, **props) -> Packet:
    p = Packet(fixed=FixedHeader(type=PT.PUBLISH, qos=qos), topic=topic,
               protocol_version=5 if v5 else 4)
    for k, v in props.items():
        setattr(p.properties, k, v)
    return p


# --- TPublishInvalid*: PublishValidate (tpackets.go:2075-2168) ---------

def test_publish_qos_must_have_packet_id():
    # TPublishInvalidQosMustPacketID [MQTT-2.2.1-3]
    p = publish(qos=1)
    p.packet_id = 0
    with pytest.raises(ProtocolError):
        p.validate_publish()


def test_publish_qos0_surplus_packet_id():
    # TPublishInvalidQos0NoPacketID [MQTT-2.2.1-2]: a qos0 publish must
    # not carry a packet id
    p = publish(qos=0)
    p.packet_id = 7
    with pytest.raises(ProtocolError):
        p.validate_publish()


def test_publish_surplus_subscription_identifier():
    # TPublishInvalidSurplusSubID [MQTT-3.3.4-6]
    p = publish(v5=True, subscription_ids=[1])
    with pytest.raises(ProtocolError):
        p.validate_publish()


@pytest.mark.parametrize("topic", ["a/+", "a/#", "+", "#", "a/+/c"])
def test_publish_surplus_wildcard(topic):
    # TPublishInvalidSurplusWildcard(2) [MQTT-3.3.2-2]
    with pytest.raises(ProtocolError):
        publish(topic=topic).validate_publish()


def test_publish_no_topic_no_alias():
    # TPublishInvalidNoTopic [MQTT-3.3.2-1]
    with pytest.raises(ProtocolError):
        publish(topic="").validate_publish()
    # ... but alias-only is legal for v5 [MQTT-3.3.2-6]
    publish(topic="", v5=True, topic_alias=3).validate_publish()


async def test_publish_topic_alias_zero_and_excess():
    # TPublishInvalidTopicAlias / TPublishInvalidExcessTopicAlias
    # [MQTT-3.3.2-8]: enforced where the reference enforces it — on the
    # broker's inbound alias resolution
    async with running_broker(topic_alias_maximum=4) as broker:
        c = await connect(broker, "c1", version=5)
        cl = broker.clients.get("c1")
        assert cl.aliases.resolve_inbound("t", 0) is None       # zero
        assert cl.aliases.resolve_inbound("t", 5) is None       # excess
        assert cl.aliases.resolve_inbound("t", 3) == "t"        # learns
        assert cl.aliases.resolve_inbound("", 3) == "t"         # resolves
        await c.disconnect()


# --- TSubscribeInvalid* / TUnsubscribeInvalid* -------------------------

async def test_subscribe_shared_no_local_rejected():
    # TSubscribeInvalidSharedNoLocal [MQTT-3.8.3-4]: the broker must
    # drop the connection on a $share filter with NoLocal
    async with running_broker() as broker:
        c = await connect(broker, "c1", version=5)
        sub = Packet(fixed=FixedHeader(type=PT.SUBSCRIBE),
                     protocol_version=5, packet_id=7,
                     filters=[Subscription(filter="$share/g/a/b",
                                           no_local=True)])
        c.writer.write(sub.encode())
        await c.writer.drain()
        await c.wait_closed(timeout=5)
        await asyncio.sleep(0.05)
        assert broker.info.clients_connected == 0


def test_subscribe_no_filters_rejected_at_decode():
    # TSubscribeInvalidNoFilters [MQTT-3.8.3-3]: wire twin is the
    # decode-time check
    wire = Packet(fixed=FixedHeader(type=PT.SUBSCRIBE),
                  protocol_version=5, packet_id=8, filters=[]).encode()
    from maxmq_tpu.protocol.packets import parse_stream
    buf = bytearray(wire)
    [(fh, body)] = list(parse_stream(buf))
    with pytest.raises((ProtocolError, MalformedPacketError)):
        Packet.decode(fh, body, 5)


def test_unsubscribe_no_filters_rejected_at_decode():
    # TUnsubscribeInvalidNoFilters [MQTT-3.10.3-2]
    wire = Packet(fixed=FixedHeader(type=PT.UNSUBSCRIBE),
                  protocol_version=5, packet_id=9, filters=[]).encode()
    from maxmq_tpu.protocol.packets import parse_stream
    buf = bytearray(wire)
    [(fh, body)] = list(parse_stream(buf))
    with pytest.raises((ProtocolError, MalformedPacketError)):
        Packet.decode(fh, body, 5)


# --- TConnectInvalid*: ConnectValidate (tpackets.go "validate" group) --
#
# The reference feeds hand-built structs to ConnectValidate; our
# enforcement is decode-time, so each case is replayed as the wire bytes
# that express the same violation. Struct states the wire cannot express
# (username present but flag clear is trailing bytes; >65535-byte fields
# cannot be length-prefixed) are asserted at the matching boundary.

def connect_wire(name="MQTT", version=4, flags=0, client_id=b"\x00\x02cl",
                 extra=b"") -> bytes:
    body = bytearray()
    body.extend(len(name).to_bytes(2, "big") + name.encode())
    body.append(version)
    body.append(flags)
    body.extend(b"\x00\x1e")            # keepalive
    if version == 5:
        body.append(0)                  # empty properties
    body.extend(client_id)
    body.extend(extra)
    return bytes([0x10, len(body)]) + bytes(body)


def decode_wire(raw: bytes, version_hint=4) -> Packet:
    from maxmq_tpu.protocol.packets import parse_stream
    buf = bytearray(raw)
    [(fh, body)] = list(parse_stream(buf))
    return Packet.decode(fh, body, version_hint)


@pytest.mark.parametrize("name,version", [
    ("stuff", 4),       # TConnectInvalidProtocolName
    ("MQTT", 2),        # TConnectInvalidProtocolVersion
    ("MQIsdp", 2),      # TConnectInvalidProtocolVersion2
])
def test_connect_bad_protocol_name_version(name, version):
    with pytest.raises(ProtocolError):
        decode_wire(connect_wire(name=name, version=version))


def test_connect_reserved_bit():
    # TConnectInvalidReservedBit [MQTT-3.1.2-3]
    with pytest.raises(ProtocolError):
        decode_wire(connect_wire(flags=0x01))


def test_connect_field_no_flag_is_trailing_garbage():
    # TConnectInvalidUsernameNoFlag / TConnectInvalidPasswordNoFlag:
    # a username/password present without its flag is, on the wire,
    # surplus bytes after the declared payload
    with pytest.raises((ProtocolError, MalformedPacketError)):
        decode_wire(connect_wire(extra=b"\x00\x04user"))


def test_connect_flag_no_password_truncates():
    # TConnectInvalidFlagNoPassword: password flag set, field missing
    # (v5: username flag may be clear — craft flags=0x40)
    with pytest.raises((ProtocolError, MalformedPacketError)):
        decode_wire(connect_wire(version=5, flags=0x40), 5)


def test_connect_oversize_fields_unencodable():
    # TConnectInvalidClientIDTooLong / UsernameTooLong / PasswordTooLong:
    # 65,536-byte fields cannot be length-prefixed on the wire; the
    # encoder is the boundary that enforces it
    from maxmq_tpu.protocol.codec import write_binary
    with pytest.raises(MalformedPacketError):
        write_binary(bytearray(), bytes(65536))
    p = Packet(fixed=FixedHeader(type=PT.CONNECT), protocol_version=4,
               client_id="x" * 65536)
    with pytest.raises(MalformedPacketError):
        p.encode()


def test_connect_will_flag_no_payload_truncates():
    # TConnectInvalidWillFlagNoPayload: will flag set, topic/payload
    # fields absent
    with pytest.raises((ProtocolError, MalformedPacketError)):
        decode_wire(connect_wire(flags=0x04))


def test_connect_will_qos_out_of_range():
    # TConnectInvalidWillFlagQosOutOfRange: the 2-bit wire field caps at
    # 3; 3 is the expressible out-of-range value
    with pytest.raises(ProtocolError):
        decode_wire(connect_wire(flags=0x04 | 0x18,
                                 extra=b"\x00\x01t\x00\x01p"))


def test_connect_surplus_retain():
    # TConnectInvalidWillSurplusRetain [MQTT-3.1.2-15]
    with pytest.raises(ProtocolError):
        decode_wire(connect_wire(flags=0x20))


# --- Ack / AUTH reason-code validity (ReasonCodeValid,
#     reference packets.go:779-829; server.go:930,951) ------------------

async def test_pubrec_invalid_reason_drops_qos_flow():
    # TPubrecInvalidReason: 0x9F (connection rate exceeded) is not a
    # legal PUBREC reason (< 0x80 codes can be invalid too); the QoS2
    # flow ends, inflight is released, on_qos_dropped fires — exercised
    # at the server processing path, where the reference enforces it
    # (server.go:930-936)
    from maxmq_tpu.hooks.base import Hook

    dropped = []

    class Spy(Hook):
        def on_qos_dropped(self, client, packet):
            dropped.append(packet.reason_code)

    async with running_broker() as broker:
        broker.hooks.add(Spy())
        sub = await connect(broker, "sub", version=5)
        cl = broker.clients.get("sub")
        out = Packet(fixed=FixedHeader(type=PT.PUBLISH, qos=2),
                     protocol_version=5, topic="a/b", payload=b"m",
                     packet_id=7)
        cl.inflight.set(out)
        broker.info.inflight += 1
        bad = Packet(fixed=FixedHeader(type=PT.PUBREC),
                     protocol_version=5, packet_id=7,
                     reason_code=codes.NoSubscriptionExisted.value)
        broker._process_pubrec(cl, bad)     # 0x11: <0x80 but invalid
        assert dropped == [codes.NoSubscriptionExisted.value]
        assert cl.inflight.get(7) is None   # flow ended
        assert broker.info.inflight == 0

        # unknown id beats the reason check [MQTT-4.3.3-7]: PUBREL
        # (not-found) is replied, no phantom drop fires
        sent = []
        cl.send = lambda p: sent.append(p)
        unknown = Packet(fixed=FixedHeader(type=PT.PUBREC),
                         protocol_version=5, packet_id=99,
                         reason_code=0x80)
        broker._process_pubrec(cl, unknown)
        assert len(dropped) == 1            # unchanged
        assert sent[0].fixed.type == PT.PUBREL
        assert (sent[0].reason_code
                == codes.ErrPacketIdentifierNotFound.value)
        # same order for PUBREL: unknown id -> PUBCOMP(not-found), even
        # with an error reason
        sent.clear()
        broker._process_pubrel(cl, Packet(
            fixed=FixedHeader(type=PT.PUBREL, qos=1),
            protocol_version=5, packet_id=99, reason_code=0x92))
        assert sent and sent[0].fixed.type == PT.PUBCOMP
        assert (sent[0].reason_code
                == codes.ErrPacketIdentifierNotFound.value)
        await sub.disconnect()


def test_reason_code_valid_table():
    # TPubrelInvalidReason / TPubcompInvalidReason /
    # TAuthInvalidReason(2) / plus positive cases
    def pk(t, reason, qos=0):
        return Packet(fixed=FixedHeader(type=t, qos=qos),
                      protocol_version=5, reason_code=reason)
    assert not pk(PT.PUBREL, 0x9F, qos=1).reason_code_valid()
    assert not pk(PT.PUBCOMP, 0x9F).reason_code_valid()
    assert not pk(PT.PUBREC, codes.NoSubscriptionExisted.value
                  ).reason_code_valid()
    assert not pk(PT.AUTH, codes.NoMatchingSubscribers.value
                  ).reason_code_valid()
    assert pk(PT.PUBREL, 0x92, qos=1).reason_code_valid()
    assert pk(PT.PUBREC, 0x10).reason_code_valid()
    assert pk(PT.AUTH, 0x18).reason_code_valid()
    assert pk(PT.PUBACK, 0x9F).reason_code_valid()   # unconstrained


async def test_auth_invalid_reason_disconnects():
    # TAuthInvalidReason(2) [MQTT-3.15.2-1]
    async with running_broker() as broker:
        c = await connect(broker, "c1", version=5)
        c.writer.write(bytes([0xF0, 2,
                              codes.NoMatchingSubscribers.value, 0]))
        await c.writer.drain()
        await c.wait_closed(timeout=5)
        await asyncio.sleep(0.05)
        assert broker.info.clients_connected == 0


# --- Remaining SUBSCRIBE / UNSUBSCRIBE validate cases ------------------

def test_subscribe_packet_id_zero_rejected():
    # TSubscribeInvalidQosMustPacketID / TUnsubscribeInvalidQosMustPacketID
    from maxmq_tpu.protocol.packets import parse_stream
    for t in (PT.SUBSCRIBE, PT.UNSUBSCRIBE):
        body = bytearray(b"\x00\x00")            # packet id 0
        if t == PT.SUBSCRIBE:
            body += b"\x00"                      # v5 empty props
            body += b"\x00\x03a/b\x00"
        else:
            body += b"\x00"
            body += b"\x00\x03a/b"
        raw = bytes([(t << 4) | 0x02, len(body)]) + bytes(body)
        buf = bytearray(raw)
        [(fh, b)] = list(parse_stream(buf))
        with pytest.raises(ProtocolError):
            Packet.decode(fh, b, 5)


def test_subscription_identifier_oversize_rejected():
    # TSubscribeInvalidIdentifierOversize: 268,435,456 needs a 5-byte
    # varint, which the wire forbids; both codec directions refuse
    from maxmq_tpu.protocol.codec import write_varint, read_varint
    with pytest.raises(MalformedPacketError):
        write_varint(bytearray(), 268_435_456)
    with pytest.raises(MalformedPacketError):
        read_varint(b"\xff\xff\xff\xff\x7f", 0)


async def test_subscribe_invalid_shared_filter():
    # TSubscribeInvalidFilter ($SHARE/#): malformed share filter must be
    # refused (reference uses it as a reference-group input to the
    # server's subscribe rejection)
    async with running_broker() as broker:
        c = await connect(broker, "c1", version=5)
        [rc] = await c.subscribe("$share/#")
        assert rc >= 0x80
        await c.disconnect()


# --- encode-direction: oversize property dropping ----------------------

def test_encode_under_drops_optional_properties():
    # TConnackDropProperties / TConnackDropPropertiesPartial /
    # TDisconnectDropProperties semantics [MQTT-3.2.2-19/20]: reason
    # string and user properties are shed, in order, when the client's
    # maximum packet size would be exceeded; other properties survive
    p = Packet(fixed=FixedHeader(type=PT.CONNACK), protocol_version=5,
               reason_code=0)
    p.properties.reason_string = "reason"
    p.properties.user_properties = [("hello", "world")]
    p.properties.server_reference = "mochi-2"
    full = p.encode()
    # generous cap: everything stays
    assert p.encode_under(len(full)) == full
    # partial: user properties no longer fit, reason string does
    partial = p.encode_under(len(full) - 5)
    assert partial is not None and len(partial) < len(full)
    from maxmq_tpu.protocol.packets import parse_stream
    [(fh, body)] = list(parse_stream(bytearray(partial)))
    got = Packet.decode(fh, body, 5)
    assert got.properties.reason_string == "reason"
    assert got.properties.user_properties == []
    assert got.properties.server_reference == "mochi-2"
    # tiny cap: both dropped, the rest survives
    tiny = p.encode_under(len(partial) - 5)
    [(fh, body)] = list(parse_stream(bytearray(tiny)))
    got = Packet.decode(fh, body, 5)
    assert got.properties.reason_string == ""
    assert got.properties.user_properties == []
    assert got.properties.server_reference == "mochi-2"
    # undroppable overflow: caller must drop the packet
    assert p.encode_under(4) is None
    # TPublishDropOversize: payload can't be shed
    pub = Packet(fixed=FixedHeader(type=PT.PUBLISH), protocol_version=5,
                 topic="a/b", payload=b"x" * 100)
    assert pub.encode_under(50) is None


# --- TDisconnect* encode cases (tpackets.go fail-state section) --------

def test_disconnect_reason_codes_roundtrip():
    # TDisconnectTakeover / ShuttingDown / SecondConnect /
    # ReceiveMaximum: encode-direction cases — the v5 reason code must
    # survive an encode/decode roundtrip
    from maxmq_tpu.protocol.packets import parse_stream
    for code in (codes.ErrSessionTakenOver, codes.ErrServerShuttingDown,
                 codes.ErrProtocolViolationSecondConnect
                 if hasattr(codes, "ErrProtocolViolationSecondConnect")
                 else codes.ErrProtocolViolation,
                 codes.ErrReceiveMaximumExceeded):
        p = Packet(fixed=FixedHeader(type=PT.DISCONNECT),
                   protocol_version=5, reason_code=code.value)
        buf = bytearray(p.encode())
        [(fh, body)] = list(parse_stream(buf))
        got = Packet.decode(fh, body, 5)
        assert got.reason_code == code.value
