"""ADR 020: macroday composed-fault harness smoke.

One tiny-knob production day end to end — the full phase ladder
(storm, fan-in/out, shed, churn, partition+heal, node kill) on a live
3-node mesh with ``cluster_fwd_durability=chained`` — scored against
the SLO sheet. The bench config runs the same harness at full knobs;
this lane proves the scheduler, the fault arming, and the scoring stay
healthy in under a minute (it also runs under the asyncio-debug CI
lane, so a leaked task or un-retrieved future fails here first).

Plus pure-arithmetic checks that scripts/bench_compare.py actually
gates the sheet's loss / recovery fields (a rename there would
silently un-gate the SLO row).
"""

import importlib.util
import json
import os

import pytest

from harness import MacroDay
from maxmq_tpu import faults


@pytest.fixture(autouse=True)
def clean_faults():
    faults.clear()
    yield
    faults.clear()


async def test_macroday_smoke_slo_sheet_passes():
    day = MacroDay(storm_clients=9, telemetry_msgs=6, command_msgs=5,
                   cut_msgs=6, parked_msgs=8, keepalive=0.5,
                   will_grace=1.0, settle_s=10.0)
    sheet = await day.run()
    assert sheet["pass"], f"SLO violations: {sheet['violations']}"
    assert sheet["pubacked_loss"] == 0
    assert sheet["pubacked_total"] > 0
    assert sheet["wills_fired"] == 1
    assert sheet["wills_delivered"] == 1
    assert sheet["takeover_session_present"]
    assert sheet["takeover_recovery_ms"] >= 0
    assert sheet["heal_convergence_ms"] >= 0
    assert sheet["shed_entered"] and sheet["shed_recovered"]
    assert sheet["relay_chain_waits"] >= 1
    # every phase ran, in order, and the fault-arming ones recorded
    # their sites (the replayability contract: armed_sites + fired
    # deltas make a failing day reproducible phase by phase)
    names = [p["name"] for p in sheet["phases"]]
    assert names == ["connect_storm", "fanin_fanout", "slow_consumer",
                     "sub_churn", "partition_heal", "node_kill"]
    by_name = {p["name"]: p for p in sheet["phases"]}
    assert by_name["slow_consumer"]["armed_sites"]
    assert by_name["partition_heal"]["armed_sites"]
    assert any(p["fired"] for p in sheet["phases"])
    # the sheet IS the bench row: it must survive the JSON round trip
    json.loads(json.dumps(sheet))
    # nothing left armed for the next test
    assert not faults.REGISTRY.any_armed()


async def test_macroday_sharded_box_same_slo_sheet():
    """ADR 021: the SAME day replays against a sharded box — the
    three roles become pool workers over unix bridge links (plus one
    extra mesh member at workers=4) and the kill phase scores as
    ``worker_kill`` through the unchanged scorer."""
    day = MacroDay(storm_clients=9, telemetry_msgs=6, command_msgs=5,
                   cut_msgs=6, parked_msgs=8, keepalive=0.5,
                   will_grace=1.0, settle_s=10.0, workers=4)
    sheet = await day.run()
    assert sheet["pass"], f"SLO violations: {sheet['violations']}"
    assert sheet["pubacked_loss"] == 0
    assert sheet["workers"] == 4 and sheet["nodes"] == 4
    assert sheet["takeover_session_present"]
    assert sheet["wills_fired"] == 1
    names = [p["name"] for p in sheet["phases"]]
    assert names[-1] == "worker_kill" and "node_kill" not in names
    # every link in the in-box mesh is a local (unix) one
    assert all(ln.local for n in ("A", "C")
               for ln in day.mgrs[n].links.values())

test_macroday_sharded_box_same_slo_sheet._async_timeout = 120


def test_bench_compare_gates_slo_fields():
    """The SLO sheet's loss / recovery / violation fields must be
    lower-better AND gated, or the macroday row stops blocking."""
    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "scripts", "bench_compare.py")
    spec = importlib.util.spec_from_file_location("bench_compare_mod",
                                                  path)
    bc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bc)
    _direction, _gated, compare = bc._direction, bc._gated, bc.compare

    for metric in ("pubacked_loss", "takeover_recovery_ms",
                   "heal_convergence_ms", "violations_count"):
        assert _direction(metric) == -1, metric
        assert _gated(metric), metric
    # ADR 021: the cshard scaling row's throughput keys are
    # higher-better AND gated; the speedup ratios stay informational
    # (a single-core box cannot promise >1x)
    for metric in ("w4_accepts_per_sec", "w2_qos0_delivered_per_sec",
                   "w4_qos1_delivered_per_sec"):
        assert _direction(metric) == 1, metric
        assert _gated(metric), metric
    assert _direction("qos1_speedup_w4") == 0
    # a zero-loss baseline regressing to ANY loss is inf delta -> gate
    old = {"macroday": {"pubacked_loss": 0.0,
                        "takeover_recovery_ms": 1000.0}}
    new = {"macroday": {"pubacked_loss": 1.0,
                        "takeover_recovery_ms": 1050.0}}
    _table, regressions = compare(old, new, threshold=0.15)
    assert [(c, m) for c, m, *_ in regressions] == \
        [("macroday", "pubacked_loss")]
    # the *_ms noise floor: a sub-ms tail tripling is sample noise
    # (flagged worse, not gated); a recovery time regressing by real
    # milliseconds still gates
    old = {"x": {"trace.p99_ms": 0.1, "takeover_recovery_ms": 1000.0}}
    new = {"x": {"trace.p99_ms": 0.3, "takeover_recovery_ms": 1400.0}}
    table, regressions = compare(old, new, threshold=0.15)
    assert [(c, m) for c, m, *_ in regressions] == \
        [("x", "takeover_recovery_ms")]
    assert [r for r in table if r[1] == "trace.p99_ms"][0][-1] == "worse"


def test_bench_compare_rtt_scaled_floor():
    """ADR 022: a row that declares ``rtt_ms`` (the geoday sheet) gets
    its *_ms noise floor scaled by the configured RTT — at 150ms RTT a
    recovery time wobbling by under one round trip is run-to-run
    noise, not a regression; past the scaled floor it still gates."""
    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "scripts", "bench_compare.py")
    spec = importlib.util.spec_from_file_location("bench_compare_mod2",
                                                  path)
    bc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bc)

    # +40% but only +120ms absolute: under the 150ms scaled floor ->
    # "worse", not gated (an unshaped row with the same move gates)
    old = {"geoday": {"rtt_ms": 150.0,
                      "outage_takeover_recovery_ms": 300.0},
           "macroday": {"takeover_recovery_ms": 300.0}}
    new = {"geoday": {"rtt_ms": 150.0,
                      "outage_takeover_recovery_ms": 420.0},
           "macroday": {"takeover_recovery_ms": 420.0}}
    table, regressions = bc.compare(old, new, threshold=0.15)
    assert [(c, m) for c, m, *_ in regressions] == \
        [("macroday", "takeover_recovery_ms")]
    geo = [r for r in table
           if r[0] == "geoday" and r[1] == "outage_takeover_recovery_ms"]
    assert geo[0][-1] == "worse"
    # past the scaled floor (and the threshold) the geoday row gates
    new = {"geoday": {"rtt_ms": 150.0,
                      "outage_takeover_recovery_ms": 600.0}}
    _t, regressions = bc.compare({"geoday": old["geoday"]}, new,
                                 threshold=0.15)
    assert [(c, m) for c, m, *_ in regressions] == \
        [("geoday", "outage_takeover_recovery_ms")]
    # a missing rtt_ms leaves the plain 1ms floor untouched
    old2 = {"y": {"takeover_recovery_ms": 10.0}}
    new2 = {"y": {"takeover_recovery_ms": 20.0}}
    _t, regressions = bc.compare(old2, new2, threshold=0.15)
    assert [(c, m) for c, m, *_ in regressions] == \
        [("y", "takeover_recovery_ms")]
