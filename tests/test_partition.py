"""Network-partition chaos harness (ADR 018): the directed
``cluster.partition`` fault family driving a multi-node cluster
through split-brain, heal, asymmetric loss, and flapping — proving

* zero PUBACKed loss across a split-brain + heal under
  ``cluster_session_sync=always`` (cross-node publisher included:
  stranded QoS1 forwards park and retry after heal, deduped by the
  receiver's per-(origin, epoch) msgid window),
* CONNECT and PUBACK never wedge under any partition mode (every
  barrier is bounded and degrades counted),
* exactly one transferred will fired per owner death (elected judge +
  epoch-fenced willfire stand-down),
* dead-owner replica expiry (seeded from replicated expiry metadata,
  returning owner wins),
* replica convergence after a relay node (middle of a 3-node line)
  restarts mid-replication-stream,
* the ADR-018 satellite gaps: held-but-unsent (quota-parked) inflight
  and the receiver-side QoS2 dedup set surviving takeover.
"""

import asyncio
import time
from contextlib import asynccontextmanager

import pytest

from maxmq_tpu import faults
from maxmq_tpu.broker import Broker, BrokerOptions, Capabilities, TCPListener
from maxmq_tpu.cluster import ClusterManager, PeerSpec
from maxmq_tpu.cluster.bridge import FWD_BUCKET
from maxmq_tpu.cluster.routes import ShareLedger
from maxmq_tpu.hooks import AllowHook
from maxmq_tpu.hooks.storage import MemoryStore, MessageRecord, StorageHook
from maxmq_tpu.mqtt_client import MQTTClient
from maxmq_tpu.protocol.codec import FixedHeader, PacketType as PT
from maxmq_tpu.protocol.packets import Packet, Will
from maxmq_tpu.protocol.properties import Properties


@pytest.fixture(autouse=True)
def clean_faults():
    faults.clear()
    yield
    faults.clear()


async def make_node(store=None, **caps) -> Broker:
    caps.setdefault("sys_topic_interval", 0)
    b = Broker(BrokerOptions(capabilities=Capabilities(**caps)))
    b.add_hook(AllowHook())
    if store is not None:
        b.add_hook(StorageHook(store))
    listener = b.add_listener(TCPListener("t", "127.0.0.1:0"))
    await b.serve()
    b.test_port = listener._server.sockets[0].getsockname()[1]
    return b


def make_manager(broker: Broker, name: str, peers: list[PeerSpec],
                 **kw) -> ClusterManager:
    kw.setdefault("keepalive", 0.25)
    kw.setdefault("backoff_initial_s", 0.05)
    kw.setdefault("backoff_max_s", 0.3)
    kw.setdefault("session_sync", "always")
    kw.setdefault("session_sync_timeout_ms", 400)
    kw.setdefault("session_takeover_timeout_ms", 400)
    kw.setdefault("replica_expiry_s", 3600.0)
    mgr = ClusterManager(broker, name, peers, **kw)
    broker.attach_cluster(mgr)
    return mgr


@asynccontextmanager
async def cluster(topology: dict[str, list[str]], stores=None, **kw):
    brokers: dict[str, Broker] = {}
    managers: dict[str, ClusterManager] = {}
    node_caps = kw.pop("node_caps", {})     # extra caps, FIRST node only
    first = next(iter(topology))
    for name in topology:
        brokers[name] = await make_node(
            store=(stores or {}).get(name),
            **(node_caps if name == first else {}))
    for name, peers in topology.items():
        specs = [PeerSpec(p, "127.0.0.1", brokers[p].test_port)
                 for p in peers]
        mgr = make_manager(brokers[name], name, specs, **kw)
        managers[name] = mgr
        await mgr.start()
    try:
        yield brokers, managers
    finally:
        for b in brokers.values():
            await b.close()


MESH = {"A": ["B", "C"], "B": ["A", "C"], "C": ["A", "B"]}
LINE = {"A": ["B"], "B": ["A", "C"], "C": ["B"]}


async def wait_for(predicate, timeout: float = 10.0, what: str = ""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        await asyncio.sleep(0.02)
    raise AssertionError(f"condition not reached in {timeout}s: {what}")


async def links_converged(managers, topology):
    await wait_for(lambda: all(m.links_up == len(topology[n])
                               for n, m in managers.items()),
                   what="all links up")


async def connect(broker: Broker, client_id: str, **kw) -> MQTTClient:
    c = MQTTClient(client_id=client_id, **kw)
    await c.connect("127.0.0.1", broker.test_port)
    return c


async def drain(cli: MQTTClient, timeout: float = 0.6) -> list[bytes]:
    got = []
    while True:
        try:
            got.append((await cli.next_message(timeout=timeout)).payload)
        except asyncio.TimeoutError:
            return got


# ----------------------------------------------------------------------
# Units: partition arming, weighted share rotation
# ----------------------------------------------------------------------


def test_partition_helpers_arm_directed_keys():
    faults.partition("A", "B")
    assert faults.armed("cluster.partition#A->B")
    assert faults.armed("cluster.partition#B->A")
    faults.heal("A", "B")
    assert not faults.armed("cluster.partition#A->B")
    faults.partition("A", "B", mode="asym")
    assert faults.armed("cluster.partition#A->B")
    assert not faults.armed("cluster.partition#B->A")
    faults.heal("A", "B")
    with pytest.raises(ValueError):
        faults.partition("A", "B", mode="nope")
    # armed directions stay armed (count=-1) across many fires
    faults.partition("A", "B", mode="hang", delay_s=0.0)
    for _ in range(5):
        assert faults.fire_detail(faults.CLUSTER_PARTITION,
                                  key="A->B") == ("hang", 0.0)
    faults.heal("A", "B")


def test_share_ledger_weighted_rotation():
    """Weighted mode rotates ownership ~proportional to live member
    counts, deterministically per token, on every node; pin mode and
    token-less callers keep the lowest-id behavior."""
    key = ("g", "f")
    ledgers = {n: ShareLedger(n, balance="weighted") for n in "ABC"}
    for led in ledgers.values():
        led.set_member("A", key, 3)
        led.set_member("B", key, 1)
    owners = []
    for token in range(200):
        picks = {n: led.owner_for(key, token)
                 for n, led in ledgers.items()}
        assert len(set(picks.values())) == 1    # all nodes agree
        owners.append(picks["A"])
        # exactly one node owns the pick
        assert sum(led.owns(key, token)
                   for led in ledgers.values()) == 1
    assert 120 <= owners.count("A") <= 180      # ~3/4 of the picks
    assert owners.count("B") >= 20              # B is not starved
    # pin fallback: no token, or balance=pin
    assert ledgers["A"].owner_for(key) == "A"
    pinned = ShareLedger("B", balance="pin")
    pinned.set_member("A", key, 1)
    pinned.set_member("B", key, 9)
    assert all(pinned.owner_for(key, t) == "A" for t in range(10))
    # empty key: owned locally (never a dropped message)
    assert ledgers["C"].owns(("g", "nope"), 7)


# ----------------------------------------------------------------------
# Tentpole: split-brain + heal, zero PUBACKed loss, never-wedge
# ----------------------------------------------------------------------


async def test_split_brain_zero_pubacked_loss():
    """A|BC split-brain under sync=always + fwd durability: every
    QoS1 publish the cross-node publisher got a PUBACK for reaches the
    remote subscriber after the heal — stranded forwards park and
    retry, the dedup window keeps redelivery exactly-once — and no
    PUBACK ever wedges (bounded degrade, counted)."""
    async with cluster(MESH) as (brokers, mgrs):
        await links_converged(mgrs, MESH)
        sub = await connect(brokers["B"], "pt-sub")
        await sub.subscribe(("t/#", 1))
        await wait_for(lambda: mgrs["A"].routes.nodes_for("t/x"),
                       what="routes at A")
        pub = await connect(brokers["A"], "pt-pub")

        pubacked = []
        for i in range(8):          # healthy phase
            await pub.publish("t/x", f"m-{i}".encode(), qos=1, timeout=5)
            pubacked.append(f"m-{i}".encode())
        faults.partition("A", "B")
        faults.partition("A", "C")      # A | B-C
        await wait_for(lambda: mgrs["A"].links_up == 0,
                       what="A isolated")
        t0 = time.monotonic()
        for i in range(8, 16):      # publishes INTO the partition
            await pub.publish("t/x", f"m-{i}".encode(), qos=1, timeout=5)
            pubacked.append(f"m-{i}".encode())
        # bounded: 8 degraded PUBACKs well under 8 * full sync timeout
        assert time.monotonic() - t0 < 6.0
        assert mgrs["A"].fwd_parked_now > 0     # stranded -> parked
        faults.heal("A", "B")
        faults.heal("A", "C")
        await links_converged(mgrs, MESH)
        for i in range(16, 20):     # post-heal phase
            await pub.publish("t/x", f"m-{i}".encode(), qos=1, timeout=5)
            pubacked.append(f"m-{i}".encode())

        got: set[bytes] = set()

        async def settle():
            got.update(await drain(sub, timeout=1.5))
            return set(pubacked) <= got

        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and not await settle():
            pass
        lost = set(pubacked) - got
        assert not lost, f"PUBACKed messages lost across the heal: {lost}"
        assert mgrs["A"].fwd_parked_resent > 0
        assert mgrs["A"].fwd_barrier_degraded > 0   # counted, not silent

        # replicas convergent within the heal window: A's and C's
        # replica of the subscriber's session carries the same digest
        # as B's live window once replication drains
        cli_b = brokers["B"].clients.get("pt-sub")

        def digests_match():
            live = cli_b.inflight.digest()
            return all(
                (e := m.sessions.ledger.get("pt-sub")) is not None
                and tuple(e.digest) == live
                for m in (mgrs["A"], mgrs["C"]))

        await wait_for(digests_match, what="replica digests converged")
        await pub.close()
        await sub.close()


async def test_partition_never_wedges_connect_or_puback():
    """With EVERY link of a node blackholed, a fresh client still
    CONNECTs (takeover/claim legs degrade bounded) and QoS1 publishes
    still ack within the degrade bounds."""
    async with cluster(MESH) as (brokers, mgrs):
        await links_converged(mgrs, MESH)
        for peer in ("B", "C"):
            faults.partition("A", peer)
        await wait_for(lambda: mgrs["A"].links_up == 0,
                       what="A isolated")
        t0 = time.monotonic()
        c = MQTTClient(client_id="pt-wedge", version=5,
                       clean_start=False, session_expiry=300)
        await asyncio.wait_for(
            c.connect("127.0.0.1", brokers["A"].test_port), timeout=5)
        await c.subscribe(("w/#", 1))
        for i in range(3):
            await c.publish("w/x", b"p", qos=1, timeout=5)
        assert time.monotonic() - t0 < 5.0
        await c.close()


async def test_asymmetric_loss_and_flapping():
    """asym A->B: A's direction blackholes (A detects its link down,
    strands+parks), while B->A still flows publishes. Then flap the
    full partition several times under load — the cluster converges
    and no PUBACKed message is lost."""
    pair = {"A": ["B"], "B": ["A"]}
    async with cluster(pair) as (brokers, mgrs):
        await links_converged(mgrs, pair)
        sub_b = await connect(brokers["B"], "asym-sub-b")
        await sub_b.subscribe(("ab/#", 1))
        sub_a = await connect(brokers["A"], "asym-sub-a")
        await sub_a.subscribe(("ba/#", 1))
        await wait_for(lambda: mgrs["A"].routes.nodes_for("ab/x")
                       and mgrs["B"].routes.nodes_for("ba/x"),
                       what="routes both ways")
        pub_a = await connect(brokers["A"], "asym-pub-a")
        pub_b = await connect(brokers["B"], "asym-pub-b")

        faults.partition("A", "B", mode="asym")     # A->B dies only
        await wait_for(lambda: not mgrs["A"].links["B"].connected,
                       what="A's link to B down")
        assert mgrs["B"].links["A"].connected       # B->A alive
        await pub_b.publish("ba/x", b"b-to-a", qos=1, timeout=5)
        assert b"b-to-a" in set(await drain(sub_a, timeout=2.0))
        await pub_a.publish("ab/x", b"a-to-b", qos=1, timeout=5)
        faults.heal("A", "B")
        got_b = set()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and b"a-to-b" not in got_b:
            got_b.update(await drain(sub_b, timeout=1.0))
        assert b"a-to-b" in got_b                   # parked -> healed

        # flapping: 3 rapid partition/heal cycles under publish load
        sent = []
        for cycle in range(3):
            faults.partition("A", "B")
            for i in range(3):
                p = f"f-{cycle}-{i}".encode()
                await pub_a.publish("ab/x", p, qos=1, timeout=5)
                sent.append(p)
            faults.heal("A", "B")
            await asyncio.sleep(0.2)
        await links_converged(mgrs, pair)
        got_b = set()
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and not set(sent) <= got_b:
            got_b.update(await drain(sub_b, timeout=1.0))
        lost = set(sent) - got_b
        assert not lost, f"flapping lost PUBACKed messages: {lost}"
        for c in (sub_a, sub_b, pub_a, pub_b):
            await c.close()


async def test_forward_parks_in_dead_read_loop_window():
    """The SIGKILL window the kill-restart drive exposed: the bridge
    client's read loop is already dead (its shutdown sweep of pending
    acks has run) but the supervisor hasn't torn the link down yet. A
    QoS1 forward enqueued in that window must park immediately — an
    ack registered on the corpse could never resolve, and the message
    would silently miss the retry-after-heal path."""
    pair = {"A": ["B"], "B": ["A"]}
    async with cluster(pair) as (brokers, mgrs):
        await links_converged(mgrs, pair)
        link = mgrs["A"].links["B"]
        link.client._closed.set()       # read loop died; link up
        assert link.connected
        ok = link.forward("$cluster/fwd/A/1/999/1/1/t/x", b"p",
                          qos=1, park=True)
        assert not ok
        assert len(link.parked) == 1


async def test_fwd_durability_off_keeps_legacy_behavior():
    """cluster_fwd_durability=off: forwards are fire-and-forget again —
    nothing parks, nothing survives the partition (the documented
    pre-018 trade), and nothing is retried at heal."""
    pair = {"A": ["B"], "B": ["A"]}
    async with cluster(pair, fwd_durability="off",
                       session_sync="batched") as (brokers, mgrs):
        await links_converged(mgrs, pair)
        sub = await connect(brokers["B"], "off-sub")
        await sub.subscribe(("t/#", 1))
        await wait_for(lambda: mgrs["A"].routes.nodes_for("t/x"),
                       what="routes at A")
        pub = await connect(brokers["A"], "off-pub")
        faults.partition("A", "B")
        await wait_for(lambda: mgrs["A"].links_up == 0, what="A cut")
        await pub.publish("t/x", b"gone", qos=1, timeout=5)
        faults.heal("A", "B")
        await links_converged(mgrs, pair)
        assert mgrs["A"].forwards_parked == 0
        assert b"gone" not in set(await drain(sub, timeout=1.0))
        await pub.close()
        await sub.close()


# ----------------------------------------------------------------------
# Tentpole: dead-owner lifecycle — will transfer + replica expiry
# ----------------------------------------------------------------------


async def test_will_fires_exactly_once_on_owner_death():
    """The owner node drops off the network with a will-carrying client
    attached: the elected judge (lowest live node id) fires the
    transferred will exactly once; its willfire broadcast stands the
    other replica down. Subscribers everywhere see ONE will."""
    async with cluster(MESH) as (brokers, mgrs):
        await links_converged(mgrs, MESH)
        for m in mgrs.values():
            m.sessions.will_grace = 0.3
        sub_b = await connect(brokers["B"], "will-sub-b")
        await sub_b.subscribe(("dead/#", 1))
        sub_c = await connect(brokers["C"], "will-sub-c")
        await sub_c.subscribe(("dead/#", 1))
        wc = MQTTClient(client_id="will-cli", version=5,
                        clean_start=False, session_expiry=600,
                        will=Will(topic="dead/will-cli", payload=b"rip",
                                  qos=1))
        await wc.connect("127.0.0.1", brokers["A"].test_port)
        await wait_for(
            lambda: all("will-cli" in m.sessions.ledger
                        and m.sessions.ledger["will-cli"].will
                        for m in (mgrs["B"], mgrs["C"])),
            what="will replicated to both replicas")
        # A drops off the network (the judges can't tell a dead node
        # from a partitioned one — that's the point)
        faults.partition("A", "B")
        faults.partition("A", "C")
        await wait_for(lambda: mgrs["B"].sessions.wills_fired
                       + mgrs["C"].sessions.wills_fired == 1,
                       timeout=8, what="exactly one will fired")
        await wait_for(lambda: mgrs["B"].sessions.wills_cleared
                       + mgrs["C"].sessions.wills_cleared == 1,
                       what="the other judge stood down by willfire")
        got_b = await drain(sub_b, timeout=1.0)
        got_c = await drain(sub_c, timeout=1.0)
        assert got_b.count(b"rip") == 1
        assert got_c.count(b"rip") == 1     # forwarded from B, once
        await asyncio.sleep(0.8)            # no late second fire
        assert (mgrs["B"].sessions.wills_fired
                + mgrs["C"].sessions.wills_fired) == 1
        for c in (sub_b, sub_c, wc):
            await c.close()


async def test_reconnect_cancels_pending_will():
    """The client reconnects at a peer before the judges' grace
    elapses: the takeover claim (higher epoch) re-owns the replica
    entries and the transferred will is cancelled — a returning client
    always wins over a suspected death."""
    async with cluster(MESH) as (brokers, mgrs):
        await links_converged(mgrs, MESH)
        for m in mgrs.values():
            m.sessions.will_grace = 0.6
        wc = MQTTClient(client_id="wr-cli", version=5,
                        clean_start=False, session_expiry=600,
                        will=Will(topic="dead/wr-cli", payload=b"rip"))
        await wc.connect("127.0.0.1", brokers["A"].test_port)
        await wait_for(
            lambda: "wr-cli" in mgrs["B"].sessions.ledger
            and mgrs["B"].sessions.ledger["wr-cli"].will,
            what="will replicated")
        faults.partition("A", "B")
        faults.partition("A", "C")
        await wait_for(lambda: not mgrs["B"].links["A"].connected,
                       what="B sees A down")
        # the client comes back at B before the grace elapses
        wc2 = MQTTClient(client_id="wr-cli", version=5,
                         clean_start=False, session_expiry=600,
                         will=Will(topic="dead/wr-cli", payload=b"rip"))
        await wc2.connect("127.0.0.1", brokers["B"].test_port)
        await asyncio.sleep(1.5)    # well past grace + stagger
        assert mgrs["B"].sessions.wills_fired == 0
        assert mgrs["C"].sessions.wills_fired == 0
        entry = mgrs["C"].sessions.ledger.get("wr-cli")
        assert entry is not None and entry.owner == "B"
        await wc2.close()
        await wc.close()


async def test_parked_will_delay_survives_owner_death():
    """ADR 019 satellite regression: the client disconnects abnormally
    (will parked in the owner's ``_will_delays`` countdown) and THEN
    the owner dies mid-countdown. Pre-fix the replicated entry stood
    peers down at disconnect, losing the will cluster-wide; now the
    disconnected entry keeps the will with its REMAINING delay and the
    judge resumes the countdown — no early fire, exactly one fire."""
    async with cluster(MESH) as (brokers, mgrs):
        await links_converged(mgrs, MESH)
        for m in mgrs.values():
            m.sessions.will_grace = 0.3
        sub_b = await connect(brokers["B"], "wd-sub-b")
        await sub_b.subscribe(("dead/#", 1))
        will = Will(topic="dead/wd-cli", payload=b"rip", qos=1,
                    properties=Properties(will_delay=2))
        wc = MQTTClient(client_id="wd-cli", version=5,
                        clean_start=False, session_expiry=600,
                        will=will)
        await wc.connect("127.0.0.1", brokers["A"].test_port)
        await wait_for(
            lambda: "wd-cli" in mgrs["B"].sessions.ledger
            and mgrs["B"].sessions.ledger["wd-cli"].will,
            what="will replicated while connected")
        await wc.close()                    # abnormal: will parks at A
        await wait_for(
            lambda: not mgrs["B"].sessions.ledger["wd-cli"].connected,
            what="disconnect replicated")
        entry = mgrs["B"].sessions.ledger["wd-cli"]
        assert entry.will is not None, "parked will lost at disconnect"
        assert 0.0 < float(entry.will[4]) <= 2.0   # REMAINING delay
        assert "wd-cli" in brokers["A"]._will_delays
        # the owner dies mid-countdown
        faults.partition("A", "B")
        faults.partition("A", "C")
        await wait_for(lambda: not mgrs["B"].links["A"].connected,
                       what="B sees A down")
        await asyncio.sleep(0.6)    # past stagger, NOT past the delay
        assert mgrs["B"].sessions.wills_fired == 0
        assert mgrs["C"].sessions.wills_fired == 0
        await wait_for(lambda: mgrs["B"].sessions.wills_fired
                       + mgrs["C"].sessions.wills_fired == 1,
                       timeout=8, what="resumed countdown fired once")
        got_b = await drain(sub_b, timeout=1.0)
        assert got_b.count(b"rip") == 1
        await asyncio.sleep(0.8)            # no late second fire
        assert (mgrs["B"].sessions.wills_fired
                + mgrs["C"].sessions.wills_fired) == 1
        await sub_b.close()
        await wc.close()


async def test_owner_local_delayed_will_fire_stands_replicas_down():
    """The owner survives and its own ``_will_delays`` countdown
    elapses: ``on_will_sent`` clears the replicated copy everywhere,
    so a LATER owner death cannot fire the will a second time from a
    stale entry."""
    async with cluster({"A": ["B"], "B": ["A"]}) as (brokers, mgrs):
        await links_converged(mgrs, {"A": ["B"], "B": ["A"]})
        sub_b = await connect(brokers["B"], "lf-sub-b")
        await sub_b.subscribe(("dead/#", 1))
        will = Will(topic="dead/lf-cli", payload=b"rip", qos=1,
                    properties=Properties(will_delay=1))
        wc = MQTTClient(client_id="lf-cli", version=5,
                        clean_start=False, session_expiry=600,
                        will=will)
        await wc.connect("127.0.0.1", brokers["A"].test_port)
        await wait_for(
            lambda: "lf-cli" in mgrs["B"].sessions.ledger
            and mgrs["B"].sessions.ledger["lf-cli"].will,
            what="will replicated")
        await wc.close()
        await wait_for(
            lambda: (await_entry := mgrs["B"].sessions.ledger.get(
                "lf-cli")) is not None and await_entry.will is not None
            and not await_entry.connected,
            what="parked will rides the disconnected entry")
        # the owner's own countdown fires it locally (~1s)
        got_b = await drain(sub_b, timeout=3.0)
        assert got_b.count(b"rip") == 1     # delivered via forward
        await wait_for(
            lambda: mgrs["B"].sessions.ledger["lf-cli"].will is None,
            what="on_will_sent replicated the stand-down")
        assert not brokers["A"]._will_delays
        assert mgrs["B"].sessions.wills_fired == 0
        await sub_b.close()
        await wc.close()


async def test_takeover_cancels_parked_will_delay():
    """The client reconnects AT A PEER while its will ticks in the old
    owner's ``_will_delays``: the takeover eviction cancels the parked
    will [MQTT-3.1.3-9] — no will fires anywhere."""
    async with cluster(MESH) as (brokers, mgrs):
        await links_converged(mgrs, MESH)
        sub_c = await connect(brokers["C"], "tc-sub-c")
        await sub_c.subscribe(("dead/#", 1))
        will = Will(topic="dead/tc-cli", payload=b"rip", qos=1,
                    properties=Properties(will_delay=2))
        wc = MQTTClient(client_id="tc-cli", version=5,
                        clean_start=False, session_expiry=600,
                        will=will)
        await wc.connect("127.0.0.1", brokers["A"].test_port)
        await wait_for(lambda: "tc-cli" in mgrs["B"].sessions.ledger,
                       what="replicated")
        await wc.close()                    # parks the will at A
        await wait_for(lambda: "tc-cli" in brokers["A"]._will_delays,
                       what="will parked")
        wc2 = MQTTClient(client_id="tc-cli", version=5,
                         clean_start=False, session_expiry=600,
                         will=Will(topic="dead/tc-cli", payload=b"rip"))
        await wc2.connect("127.0.0.1", brokers["B"].test_port)
        await wait_for(lambda: "tc-cli" not in brokers["A"]._will_delays,
                       what="takeover cancelled the parked will")
        await asyncio.sleep(2.4)            # past the original delay
        assert await drain(sub_c, timeout=0.5) == []
        for m in mgrs.values():
            assert m.sessions.wills_fired == 0
        await wc2.close()
        await sub_c.close()


def _scripted_entry(cid: str, owner: str, will_delay: float,
                    connected: bool, expiry: int = 0) -> "SessionEntry":
    from maxmq_tpu.cluster.sessions import SessionEntry
    return SessionEntry(cid, owner, session_epoch=3, boot_epoch=7,
                        expiry=expiry, expiry_set=bool(expiry),
                        connected=connected,
                        will=["dead/" + cid, b"rip".hex(), 1, 0,
                              will_delay])


async def test_scripted_clock_will_countdown_resume():
    """Deterministic ``_sweep_entry`` arithmetic (no sleeps): a
    disconnected entry's will fires when BOTH the judge stagger (from
    owner death) and the remaining delay (from the disconnect the
    judge observed) have elapsed — not before either, not restarted
    from owner death — and a connected entry keeps the ADR-018 clock
    (stagger + full delay from death)."""
    pair = {"A": ["B"], "B": ["A"]}
    async with cluster(pair) as (brokers, mgrs):
        fed = mgrs["B"].sessions
        fed.will_grace = 0.3
        fed._started_mono = 1000.0      # owner "Z" death observed here
        # -- disconnected entry: countdown resumes from the observed
        #    disconnect, NOT from owner death
        e = _scripted_entry("sc-d", "Z", will_delay=5.0, connected=False)
        e.disconnected_seen = 990.0     # disconnected 10s before death
        fed.ledger["sc-d"] = e
        fed._sweep_entry(e, 1000.2, rank=0)     # stagger not elapsed
        assert e.will is not None and fed.wills_fired == 0
        # stagger elapsed AND 990+5 delay long since elapsed -> fire.
        # (pre-fix: disconnected entries never fired; a restart-at-
        # death bug would demand now >= 1000 + 0.3 + 5.0)
        fed._sweep_entry(e, 1000.4, rank=0)
        assert e.will is None and fed.wills_fired == 1
        # -- disconnected entry whose remaining delay is NOT yet up
        e2 = _scripted_entry("sc-r", "Z", will_delay=5.0,
                             connected=False)
        e2.disconnected_seen = 998.0
        fed.ledger["sc-r"] = e2
        fed._sweep_entry(e2, 1002.0, rank=0)    # 4.0 of 5.0 elapsed
        assert e2.will is not None and fed.wills_fired == 1
        # a rank-1 judge staggers the FIRE instant (delay + one grace):
        # every judge's countdown expires at the same moment, so the
        # stand-down window must sit between the ranks' fire times
        fed._sweep_entry(e2, 1003.2, rank=1)    # 5.2 < 5.0 + 0.3
        assert e2.will is not None and fed.wills_fired == 1
        fed._sweep_entry(e2, 1003.1, rank=0)    # 5.1 of 5.0 -> fires
        assert e2.will is None and fed.wills_fired == 2
        # -- connected entry: unchanged ADR-018 clock, death + stagger
        #    + full delay (rank stagger honored)
        e3 = _scripted_entry("sc-c", "Z", will_delay=2.0, connected=True)
        fed.ledger["sc-c"] = e3
        fed._sweep_entry(e3, 1002.2, rank=1)    # 2.2 < 0.6 + 2.0
        assert e3.will is not None and fed.wills_fired == 2
        fed._sweep_entry(e3, 1002.7, rank=1)    # 2.7 >= 2.6 -> fires
        assert e3.will is None and fed.wills_fired == 3
        # -- expiring entry fires its pending will on the way out
        e4 = _scripted_entry("sc-x", "Z", will_delay=600.0,
                             connected=False, expiry=1)
        e4.disconnected_seen = 999.0
        fed.ledger["sc-x"] = e4
        fed._sweep_entry(e4, 1000.5, rank=0)    # expiry 1s + stagger up
        assert fed.wills_fired == 4 and e4.will is None
        assert fed.replica_expiries == 1
        assert "sc-x" not in fed.ledger
        for cid in ("sc-d", "sc-r", "sc-c"):
            fed.ledger.pop(cid, None)


async def test_replica_expiry_purges_dead_owners_sessions():
    """A disconnected session whose owner then dies: the judge's
    replica-side timer (seeded from the replicated session expiry)
    purges the orphan, broadcasts the epoch-fenced third-party purge
    (transitive holders purge too), and leaves a tombstone so a
    re-created session claims above the dead epoch."""
    async with cluster(MESH) as (brokers, mgrs):
        await links_converged(mgrs, MESH)
        for m in mgrs.values():
            m.sessions.will_grace = 0.2     # stagger base
        c = MQTTClient(client_id="exp-cli", version=5,
                       clean_start=False, session_expiry=1)
        await c.connect("127.0.0.1", brokers["A"].test_port)
        await c.subscribe(("e/#", 1))
        await wait_for(lambda: "exp-cli" in mgrs["B"].sessions.ledger,
                       what="replicated")
        await c.disconnect()
        await c.close()
        await wait_for(
            lambda: not mgrs["B"].sessions.ledger["exp-cli"].connected,
            what="disconnect replicated")
        faults.partition("A", "B")
        faults.partition("A", "C")
        await wait_for(lambda: "exp-cli" not in mgrs["B"].sessions.ledger,
                       timeout=8, what="B expired the replica")
        assert mgrs["B"].sessions.replica_expiries == 1
        await wait_for(lambda: "exp-cli" not in mgrs["C"].sessions.ledger,
                       what="C purged via broadcast")
        assert mgrs["B"].sessions._tombstones.get("exp-cli", 0) >= 1


async def test_replica_expiry_returning_owner_wins():
    """The owner heals before the expiry elapses: the countdown is
    fenced — the replica survives and reconnects keep working."""
    async with cluster(MESH) as (brokers, mgrs):
        await links_converged(mgrs, MESH)
        c = MQTTClient(client_id="ret-cli", version=5,
                       clean_start=False, session_expiry=2)
        await c.connect("127.0.0.1", brokers["A"].test_port)
        await c.subscribe(("r/#", 1))
        await wait_for(lambda: "ret-cli" in mgrs["B"].sessions.ledger,
                       what="replicated")
        faults.partition("A", "B")
        faults.partition("A", "C")
        await wait_for(lambda: not mgrs["B"].links["A"].connected,
                       what="B sees A down")
        faults.heal("A", "B")
        faults.heal("A", "C")
        await links_converged(mgrs, MESH)
        await asyncio.sleep(1.0)    # countdown must have reset
        assert "ret-cli" in mgrs["B"].sessions.ledger
        assert mgrs["B"].sessions.replica_expiries == 0
        await c.close()


# ----------------------------------------------------------------------
# Satellites: pubrec streaming, held replication, relay restart
# ----------------------------------------------------------------------


async def test_pubrec_pending_streams_to_replicas():
    """The broker-side inbound QoS2 dedup set (PUBREC sent, PUBREL
    pending) streams as replication ops — a replica holds it WITHOUT a
    state pull, so a dead-owner failover keeps deduping retried
    publishes."""
    pair = {"A": ["B"], "B": ["A"]}
    async with cluster(pair) as (brokers, mgrs):
        await links_converged(mgrs, pair)
        c = MQTTClient(client_id="q2-cli", version=5,
                       clean_start=False, session_expiry=600)
        await c.connect("127.0.0.1", brokers["A"].test_port)
        leftover = await c.pause_reading()   # manual QoS2 state machine
        assert not leftover
        pkt = Packet(fixed=FixedHeader(type=PT.PUBLISH, qos=2),
                     protocol_version=5, topic="q2/x", payload=b"z",
                     packet_id=77)
        c.writer.write(pkt.encode())
        await c.writer.drain()
        await wait_for(
            lambda: 77 in (mgrs["B"].sessions.ledger.get("q2-cli").pubrec
                           if mgrs["B"].sessions.ledger.get("q2-cli")
                           else []),
            what="pubrec streamed to B")
        rel = Packet(fixed=FixedHeader(type=PT.PUBREL),
                     protocol_version=5, packet_id=77)
        c.writer.write(rel.encode())
        await c.writer.drain()
        await wait_for(
            lambda: 77 not in mgrs["B"].sessions.ledger["q2-cli"].pubrec,
            what="pubrec release streamed to B")
        await c.close()


async def test_held_inflight_replicates_and_survives_takeover():
    """Quota-parked (held-but-unsent) messages replicate with their
    held flag and survive a cross-node takeover: the new owner re-parks
    them and drains within the receive window — nothing is dropped,
    nothing overruns the client's receive maximum."""
    pair = {"A": ["B"], "B": ["A"]}
    stores = {"A": MemoryStore(), "B": MemoryStore()}
    async with cluster(pair, stores=stores,
                       node_caps={"receive_maximum": 1}) as (brokers,
                                                             mgrs):
        # receive_maximum=1 applies to node A (first topology entry)
        await links_converged(mgrs, pair)
        sub = MQTTClient(client_id="held-sub", version=5,
                         clean_start=False, session_expiry=600)
        await sub.connect("127.0.0.1", brokers["A"].test_port)
        await sub.subscribe(("h/#", 1))
        await wait_for(lambda: "held-sub" in mgrs["B"].sessions.ledger,
                       what="session replicated")
        await sub.pause_reading()       # stop acking: quota stays taken
        pub = await connect(brokers["A"], "held-pub")
        for i in range(3):
            await pub.publish("h/x", f"h-{i}".encode(), qos=1, timeout=5)
        cli = brokers["A"].clients.get("held-sub")
        await wait_for(lambda: len(cli.held_pids) == 2,
                       what="two messages quota-parked")
        entry = mgrs["B"].sessions.ledger["held-sub"]
        await wait_for(lambda: len(entry.inflight) == 3,
                       what="all three replicated")
        held_flags = sorted(
            MessageRecord.from_json(raw).held
            for raw in entry.inflight.values())
        assert held_flags == [False, True, True]
        # local journal carries held too (ADR-014 leg of the satellite)
        stored = [MessageRecord.from_json(v) for k, v in
                  stores["A"].all("inflight").items()
                  if k.startswith("held-sub|")]
        assert sorted(r.held for r in stored) == [False, True, True]

        # takeover at B: held messages re-park, then drain under quota
        sub2 = MQTTClient(client_id="held-sub", version=5,
                          clean_start=False, session_expiry=600)
        await sub2.connect("127.0.0.1", brokers["B"].test_port)
        assert sub2.session_present
        got = set()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and len(got) < 3:
            got.update(await drain(sub2, timeout=1.0))
        assert got == {b"h-0", b"h-1", b"h-2"}
        await sub2.close()
        await pub.close()
        await sub.close()


async def test_relay_restart_mid_stream_converges():
    """The middle node of a 3-node line restarts while an inflight
    replication stream is flowing A -> B -> C: after B returns (new
    boot epoch, fresh link), A's resync re-ships the full window and
    the transitive relay converges C's replica to A's live state."""
    async with cluster(LINE) as (brokers, mgrs):
        await links_converged(mgrs, LINE)
        sub = MQTTClient(client_id="rel-sub", version=5,
                         clean_start=False, session_expiry=600)
        await sub.connect("127.0.0.1", brokers["A"].test_port)
        await sub.subscribe(("rl/#", 1))
        await sub.pause_reading()       # unacked: window accumulates
        await wait_for(lambda: "rel-sub" in mgrs["C"].sessions.ledger,
                       what="session reached C transitively")
        pub = await connect(brokers["A"], "rel-pub")
        for i in range(5):
            await pub.publish("rl/x", f"r-{i}".encode(), qos=1, timeout=5)

        # restart B on the same port, mid-stream
        port_b = brokers["B"].test_port
        await brokers["B"].close()
        for i in range(5, 10):
            await pub.publish("rl/x", f"r-{i}".encode(), qos=1, timeout=5)
        b2 = Broker(BrokerOptions(
            capabilities=Capabilities(sys_topic_interval=0)))
        b2.add_hook(AllowHook())
        lst = b2.add_listener(TCPListener("t", f"127.0.0.1:{port_b}"))
        await b2.serve()
        b2.test_port = port_b
        mgr_b2 = make_manager(
            b2, "B", [PeerSpec("A", "127.0.0.1", brokers["A"].test_port),
                      PeerSpec("C", "127.0.0.1", brokers["C"].test_port)])
        await mgr_b2.start()
        brokers["B"] = b2               # the fixture closes the new one
        mgrs["B"] = mgr_b2
        await links_converged(mgrs, LINE)
        for i in range(10, 12):
            await pub.publish("rl/x", f"r-{i}".encode(), qos=1, timeout=5)

        cli = brokers["A"].clients.get("rel-sub")

        def converged(m):
            e = m.sessions.ledger.get("rel-sub")
            return (e is not None and e.owner == "A"
                    and set(e.inflight) == {p.packet_id
                                            for p in cli.inflight.all()})

        await wait_for(lambda: converged(mgrs["B"]),
                       what="B replica converged after restart")
        await wait_for(lambda: converged(mgrs["C"]),
                       what="C replica converged through the relay")
        await pub.close()
        await sub.close()


# ----------------------------------------------------------------------
# Satellites: parked-forward journal restore, weighted $share e2e
# ----------------------------------------------------------------------


async def test_restored_offline_session_queues_publishes():
    """A session restored from the journal after a restart is a
    DISCONNECTED session: publishes arriving before the client returns
    must queue in its inflight window (they were refused+rolled back
    as slow-consumer drops — the restored Client object never ran
    stop(), so `closed` was False; found by the ADR-018 kill-restart
    verify drive)."""
    store = MemoryStore()
    b1 = await make_node(store=store)
    sub = MQTTClient(client_id="ro-sub", version=5, clean_start=False,
                     session_expiry=3600)
    await sub.connect("127.0.0.1", b1.test_port)
    await sub.subscribe(("ro/#", 1))
    await sub.close()
    await b1.close()

    b2 = await make_node(store=store)        # restore: session offline
    cli = b2.clients.get("ro-sub")
    assert cli is not None and cli.closed    # restored == disconnected
    pub = await connect(b2, "ro-pub")
    await pub.publish("ro/x", b"queued", qos=1, timeout=5)
    assert len(cli.inflight) == 1            # parked for the resume
    sub2 = MQTTClient(client_id="ro-sub", version=5, clean_start=False,
                      session_expiry=3600)
    await sub2.connect("127.0.0.1", b2.test_port)
    assert sub2.session_present
    assert (await sub2.next_message(timeout=5)).payload == b"queued"
    await sub2.close()
    await pub.close()
    await b2.close()


async def test_parked_forwards_survive_node_restart():
    """A partition strands QoS1 forwards at A (journaled in the
    cluster_fwd bucket); A then crashes and restarts: the parked
    forwards restore from the journal and deliver after the link
    heals — cross-node publish durability survives BOTH failure
    modes."""
    pair = {"A": ["B"], "B": ["A"]}
    store_a = MemoryStore()
    async with cluster(pair, stores={"A": store_a}) as (brokers, mgrs):
        await links_converged(mgrs, pair)
        sub = await connect(brokers["B"], "pk-sub")
        await sub.subscribe(("pk/#", 1))
        await wait_for(lambda: mgrs["A"].routes.nodes_for("pk/x"),
                       what="routes at A")
        pub = await connect(brokers["A"], "pk-pub")
        faults.partition("A", "B")
        await wait_for(lambda: mgrs["A"].links_up == 0, what="A cut")
        await pub.publish("pk/x", b"parked", qos=1, timeout=5)
        await wait_for(lambda: mgrs["A"].fwd_parked_now >= 1,
                       what="forward parked")
        await wait_for(lambda: store_a.all(FWD_BUCKET),
                       what="parked forward journaled")
        await pub.close()

        # "crash" A, restart on the same store (heal first so the new
        # incarnation's link comes straight up)
        port_a = brokers["A"].test_port
        await brokers["A"].close()
        faults.heal("A", "B")
        a2 = Broker(BrokerOptions(
            capabilities=Capabilities(sys_topic_interval=0)))
        a2.add_hook(AllowHook())
        a2.add_hook(StorageHook(store_a))
        a2.add_listener(TCPListener("t", f"127.0.0.1:{port_a}"))
        await a2.serve()
        a2.test_port = port_a
        mgr_a2 = make_manager(
            a2, "A", [PeerSpec("B", "127.0.0.1", brokers["B"].test_port)])
        await mgr_a2.start()
        assert mgr_a2.fwd_parked_now == 1       # restored from journal
        brokers["A"] = a2
        mgrs["A"] = mgr_a2
        got = set()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and b"parked" not in got:
            got.update(await drain(sub, timeout=1.0))
        assert b"parked" in got
        await wait_for(lambda: not store_a.all(FWD_BUCKET),
                       what="journal row cleared on ack")
        await sub.close()


async def test_weighted_share_exactly_once_and_balanced():
    """ADR-018 fairness: a $share group with 2 members at B and 1 at C
    stays exactly-once cluster-wide under weighted rotation, and BOTH
    nodes receive picks (the old pin starved everyone but the lowest
    node id)."""
    async with cluster(MESH, session_sync="batched") as (brokers, mgrs):
        await links_converged(mgrs, MESH)
        members = {}
        for name, n in (("B", 2), ("C", 1)):
            for k in range(n):
                m = await connect(brokers[name], f"shw-{name}{k}")
                await m.subscribe(("$share/g/ws/t", 0))
                members[f"{name}{k}"] = m
        key = ("g", "$share/g/ws/t")
        await wait_for(
            lambda: all(
                sorted(mgr.routes.shares.members_for(key)) == ["B", "C"]
                for mgr in mgrs.values()),
            what="share membership converged everywhere")
        pub = await connect(brokers["A"], "shw-pub")
        n_msgs = 60
        for i in range(n_msgs):
            await pub.publish("ws/t", f"weighted-payload-{i * 7}".encode())
        per_member = {name: await drain(m, timeout=1.0)
                      for name, m in members.items()}
        all_payloads = [p for got in per_member.values() for p in got]
        assert len(all_payloads) == n_msgs, \
            f"not exactly-once: {len(all_payloads)} != {n_msgs}"
        assert len(set(all_payloads)) == n_msgs
        per_node = {"B": len(per_member["B0"]) + len(per_member["B1"]),
                    "C": len(per_member["C0"])}
        assert per_node["B"] > 0 and per_node["C"] > 0, per_node
        # 2 members vs 1: this payload set hashes 36/24 toward B
        assert per_node["B"] > per_node["C"], per_node
        for m in list(members.values()) + [pub]:
            await m.close()


# ----------------------------------------------------------------------
# ADR 020: multi-hop chained forward durability + sub-keepalive blips
# ----------------------------------------------------------------------


async def test_chained_relay_kill_middle_zero_pubacked_loss():
    """ADR 020 tentpole: on a 3-node line A-B-C with
    ``cluster_fwd_durability=chained`` the relay (B) defers its
    upstream fwd-PUBACK until its own downstream forward is acked or
    journaled — so the publisher's PUBACK at A means the FAR node
    holds the message, and killing the middle node after the PUBACK
    loses nothing. A dark downstream leg must degrade bounded (the
    ``fwd_barrier_*``/``relay_chain_*`` counters), never wedge the
    publisher."""
    async with cluster(LINE, fwd_durability="chained") as (brokers,
                                                           mgrs):
        await links_converged(mgrs, LINE)
        sub = await connect(brokers["C"], "rl-sub")
        await sub.subscribe(("rl/#", 1))
        pub = await connect(brokers["A"], "rl-pub")
        await wait_for(lambda: bool(mgrs["A"].routes.nodes_for("rl/m")),
                       what="A learned the 2-hop route")
        sent = []
        for i in range(5):              # healthy leg, PUBACK-paced
            await pub.publish("rl/m", f"h-{i}".encode(), qos=1)
            sent.append(f"h-{i}".encode())
        # hop-chained: by PUBACK time the relay has already collected
        # its downstream ack — C holds every message NOW
        assert mgrs["B"].relay_chain_waits >= 5
        assert mgrs["B"].relay_chain_timeouts == 0
        got = set(await drain(sub))
        assert set(sent) <= got, "PUBACKed => already at the far node"

        # dark downstream leg: B parks the relayed copies; the chain
        # settles immediately (parked == journal-bound), PUBACK stays
        # bounded and the degrade is counted — never a wedge
        faults.partition("B", "C")
        await wait_for(lambda: not mgrs["B"].links["C"].connected,
                       what="B-C leg dark")
        t0 = time.monotonic()
        for i in range(3):
            await pub.publish("rl/m", f"d-{i}".encode(), qos=1)
            sent.append(f"d-{i}".encode())
        assert time.monotonic() - t0 < 10.0, "PUBACK wedged"
        assert (mgrs["B"].forwards_parked >= 1
                or mgrs["B"].relay_chain_timeouts >= 1)
        # CONNECT never wedges either while the leg is dark
        probe = await connect(brokers["A"], "rl-probe")
        await probe.close()

        faults.heal("B", "C")
        await wait_for(lambda: mgrs["B"].links["C"].connected,
                       timeout=15, what="B-C healed")
        await wait_for(lambda: mgrs["B"].fwd_parked_now == 0,
                       timeout=15, what="relay drained its park")
        # NOW kill the middle node: every PUBACKed message already
        # crossed to C, so the kill cannot un-deliver anything
        await brokers["B"].close()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not set(sent) <= got:
            got.update(await drain(sub, timeout=1.0))
        assert set(sent) <= got, \
            f"lost after relay kill: {set(sent) - got}"
        for c in (sub, pub):
            await c.close()


async def test_sub_keepalive_blip_detected_and_resynced():
    """ADR 020 satellite: a drop window healed before any keepalive
    flap (counted arming: EXACTLY the next 3 A->B writer items vanish,
    then the path is clean) is caught by the next audit heartbeat's
    item deficit — the receiver notices, the sender resyncs (pending
    fwd acks fail -> re-park -> drain), and every PUBACKed payload is
    delivered with the link never flapping."""
    pair = {"A": ["B"], "B": ["A"]}
    async with cluster(pair, keepalive=1.0) as (brokers, mgrs):
        await links_converged(mgrs, pair)
        sub = await connect(brokers["B"], "bl-sub")
        await sub.subscribe(("bl/#", 1))
        pubs = [await connect(brokers["A"], f"bl-pub{i}")
                for i in range(3)]
        await wait_for(lambda: bool(mgrs["A"].routes.nodes_for("bl/m")),
                       what="route to B")
        await pubs[0].publish("bl/m", b"pre", qos=1)
        assert await drain(sub) == [b"pre"]
        flaps0 = mgrs["A"].link_flaps + mgrs["B"].link_flaps
        blip_site = (f"{faults.CLUSTER_PARTITION}#"
                     f"{faults.partition_key('A', 'B')}")
        # phase-align to the audit heartbeat: arm right AFTER a beat so
        # the drop window sits mid-interval — the liveness fire in the
        # keepalive loop hits the same site and would flap the link
        hb0 = mgrs["A"].links["B"].hb_seq
        await wait_for(lambda: mgrs["A"].links["B"].hb_seq > hb0,
                       what="beat boundary")
        faults.arm(blip_site, "drop", count=3)
        sent = [f"b-{i}".encode() for i in range(3)]
        # one publisher per payload: the broker serializes a single
        # connection's pipelined QoS1 publishes behind the fwd barrier,
        # which would stretch the armed window past the next beat —
        # separate connections enqueue (and blackhole) all 3 forwards
        # within milliseconds, so the next beat crosses a clean path
        # and the link NEVER flaps
        await asyncio.gather(
            *(p.publish("bl/m", m, qos=1)
              for p, m in zip(pubs, sent)))
        assert not faults.armed(blip_site), "drop window self-healed"
        await wait_for(lambda: mgrs["B"].blips_detected >= 1,
                       timeout=8, what="deficit caught by next hb")
        await wait_for(lambda: mgrs["A"].blip_resyncs >= 1,
                       timeout=8, what="sender resynced")
        got = set()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not set(sent) <= got:
            got.update(await drain(sub, timeout=1.0))
        assert set(sent) <= got, f"blip lost {set(sent) - got}"
        assert mgrs["A"].fwd_parked_resent >= 1
        assert mgrs["A"].link_flaps + mgrs["B"].link_flaps == flaps0, \
            "recovery must not have come from a link flap"
        for c in (sub, *pubs):
            await c.close()


async def test_scripted_clock_will_wall_deadline_cold_entry():
    """ADR 020 satellite: a 6-element transferred will carries the
    ABSOLUTE wall-clock deadline, so a judge that applied the entry
    cold (restart / late join: no local ``disconnected_seen``) fires
    on the owner's original schedule instead of re-charging the full
    delay from owner death. 5-element (older-peer) and malformed
    entries keep the legacy duration fallback; the rank stagger
    applies at the fire instant."""
    pair = {"A": ["B"], "B": ["A"]}
    async with cluster(pair) as (brokers, mgrs):
        fed = mgrs["B"].sessions
        fed.will_grace = 0.3
        fed._started_mono = 1000.0      # owner "Z" death observed here
        wall = [5000.0]
        fed._wall = lambda: wall[0]
        # cold entry, deadline 4s out: stagger elapsed, deadline not
        e = _scripted_entry("wd-c", "Z", will_delay=600.0,
                            connected=False)
        e.will.append(5004.0)
        fed.ledger["wd-c"] = e
        fed._sweep_entry(e, 1000.4, rank=0)
        assert e.will is not None and fed.wills_fired == 0
        # deadline reached -> fires NOW (the duration fallback would
        # have re-charged 600s from owner death)
        wall[0] = 5004.1
        fed._sweep_entry(e, 1000.5, rank=0)
        assert e.will is None and fed.wills_fired == 1
        # rank-1 judge staggers the FIRE instant one grace past the
        # deadline, leaving the rank-0 stand-down its window
        e2 = _scripted_entry("wd-r", "Z", will_delay=600.0,
                             connected=False)
        e2.will.append(5004.0)
        fed.ledger["wd-r"] = e2
        wall[0] = 5004.2                # 0.2 past deadline < 0.3 grace
        fed._sweep_entry(e2, 1000.7, rank=1)
        assert e2.will is not None and fed.wills_fired == 1
        wall[0] = 5004.4
        fed._sweep_entry(e2, 1000.8, rank=1)
        assert e2.will is None and fed.wills_fired == 2
        # the death stagger still gates a long-overdue deadline
        e3 = _scripted_entry("wd-s", "Z", will_delay=0.0,
                             connected=False)
        e3.will.append(4000.0)          # long past due
        fed.ledger["wd-s"] = e3
        fed._sweep_entry(e3, 1000.1, rank=0)    # down 0.1 < 0.3
        assert e3.will is not None and fed.wills_fired == 2
        fed._sweep_entry(e3, 1000.4, rank=0)
        assert e3.will is None and fed.wills_fired == 3
        # malformed 6th element: duration fallback, never a crash
        e4 = _scripted_entry("wd-m", "Z", will_delay=0.2,
                             connected=False)
        e4.will.append("junk")
        fed.ledger["wd-m"] = e4
        fed._sweep_entry(e4, 1000.4, rank=0)    # 0.4 < 0.3 + 0.2
        assert e4.will is not None and fed.wills_fired == 3
        fed._sweep_entry(e4, 1000.6, rank=0)    # 0.6 >= 0.5 -> fires
        assert e4.will is None and fed.wills_fired == 4
        for cid in ("wd-c", "wd-r", "wd-s", "wd-m"):
            fed.ledger.pop(cid, None)


async def test_hop_capped_relay_drop_attributed_to_bridge_stage():
    """ADR 020 small fix: a relay dropping an onward forward at the
    hop cap is EXPLAINED cross-node loss — it must show up on the
    relay's ADR-015 stage-error counter (stage=bridge, reason=hop_cap)
    next to the aggregate ``hops_dropped``, so a macroday loss
    investigation lands on the right node and reason."""
    async with cluster(LINE, max_hops=1) as (brokers, mgrs):
        await links_converged(mgrs, LINE)
        sub = await connect(brokers["C"], "hc-sub")
        await sub.subscribe(("hc/#", 1))
        pub = await connect(brokers["A"], "hc-pub")
        await wait_for(lambda: bool(mgrs["A"].routes.nodes_for("hc/m")),
                       what="A learned the transitive route")
        await pub.publish("hc/m", b"capped", qos=1)
        # hop 1 (A->B) lands; the onward B->C hop sits AT the cap
        await wait_for(lambda: mgrs["B"].hops_dropped >= 1,
                       what="relay dropped at the hop cap")
        errs = dict(brokers["B"].tracer.stage_errors)
        assert errs.get(("bridge", "hop_cap"), 0) >= 1
        assert await drain(sub, timeout=0.5) == []
        for c in (sub, pub):
            await c.close()


async def test_restarted_relay_holds_fwds_until_route_sync():
    """ADR 020 (found by the live 3-node verify drive): a relay that
    restarts mid-heal can receive the upstream's parked-forward drain
    BEFORE the downstream peer's route snapshot arrives — pre-fix it
    fanned out against an empty route table, relayed nothing onward,
    acked upstream anyway, and a PUBACKed message was gone for good.
    The route-sync gate holds inbound forwards (bounded) until every
    configured peer advertised once, so the drain lands on a
    converged table."""
    async with cluster(LINE, fwd_durability="chained",
                       session_sync_timeout_ms=1500) as (brokers, mgrs):
        await links_converged(mgrs, LINE)
        sub = await connect(brokers["C"], "rs-sub", version=5,
                            clean_start=False, session_expiry=600)
        await sub.subscribe(("rs/#", 1))
        pub = await connect(brokers["A"], "rs-pub")
        await wait_for(lambda: bool(mgrs["A"].routes.nodes_for("rs/m")),
                       what="A learned the 2-hop route")
        sent = []
        for i in range(2):
            await pub.publish("rs/m", f"h-{i}".encode(), qos=1)
            sent.append(f"h-{i}".encode())
        got = set(await drain(sub))
        assert set(sent) <= got

        # kill the relay; publishes still PUBACK (parked at A)
        port_b = brokers["B"].test_port
        await brokers["B"].close()
        await wait_for(lambda: not mgrs["A"].links["B"].connected,
                       what="A saw the relay die")
        for i in range(3):
            await pub.publish("rs/m", f"d-{i}".encode(), qos=1,
                              timeout=10)
            sent.append(f"d-{i}".encode())
        assert mgrs["A"].forwards_parked >= 3

        # keep C's advertisements away from the restarted B: only the
        # C->B direction is dark, so A's drain reaches B while B's
        # route table still has no idea C subscribed anything
        cb_site = f"{faults.CLUSTER_PARTITION}#" \
                  f"{faults.partition_key('C', 'B')}"
        faults.arm(cb_site, "drop", count=-1)
        b2 = Broker(BrokerOptions(
            capabilities=Capabilities(sys_topic_interval=0)))
        b2.add_hook(AllowHook())
        b2.add_listener(TCPListener("t", f"127.0.0.1:{port_b}"))
        await b2.serve()
        b2.test_port = port_b
        mgr_b2 = make_manager(
            b2, "B", [PeerSpec("A", "127.0.0.1", brokers["A"].test_port),
                      PeerSpec("C", "127.0.0.1", brokers["C"].test_port)],
            fwd_durability="chained", session_sync_timeout_ms=1500)
        await mgr_b2.start()
        brokers["B"] = b2
        mgrs["B"] = mgr_b2

        # A drains its park into B; the gate must HOLD (C unsynced)
        await wait_for(lambda: mgr_b2.route_sync_waits >= 1,
                       what="restarted relay held the drained fwds")
        faults.disarm(cb_site)              # heal: C's snapshot lands
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and not set(sent) <= got:
            got.update(await drain(sub, timeout=1.0))
        assert set(sent) <= got, \
            f"PUBACKed loss through restarted relay: {set(sent) - got}"
        assert mgr_b2.route_sync_timeouts == 0
        for c in (sub, pub):
            await c.close()
