"""Replay the reference's packet-conformance corpus against our codec.

Fixtures: tests/fixtures/tpackets.json — wire vectors extracted from
vendor/github.com/mochi-co/mqtt/v2/packets/tpackets.go (see
tools/port_tpackets.py). Assertions per case:

* ``fail_first`` set  -> decoding the bytes must raise (the reference's
  XxxDecode returns that error);
* ``primary``         -> decode must succeed AND re-encoding the decoded
  packet must reproduce the wire bytes exactly (the reference runs these
  through its read/write symmetry harness);
* otherwise           -> decode must succeed (bytes may be a
  non-canonical encoding of the same packet).
"""

import json
import os

import pytest

from maxmq_tpu.protocol.codec import MalformedPacketError
from maxmq_tpu.protocol.packets import Packet, ProtocolError, parse_stream

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures",
                        "tpackets.json")

with open(FIXTURES, encoding="utf-8") as fh:
    CASES = [c for c in json.load(fh) if c["ptype"] != 0]

assert len(CASES) >= 100, "conformance corpus went missing"


def infer_version(case: dict) -> int:
    if case["protocol_version"]:
        return case["protocol_version"]
    name = case["case"] + case.get("desc", "")
    if "Mqtt5" in name or "mqtt v5" in name or "mqtt 5" in name:
        return 5
    if "Mqtt31" in name and "Mqtt311" not in name:
        return 3
    return 4


def frame_lenient(raw: bytes):
    """Fixed header + body exactly as the reference's decode tests feed
    it: the body is whatever follows the header, even when shorter than
    the declared remaining length (the malformed fixtures are truncated
    on purpose; mochi hands the short slice straight to XxxDecode)."""
    from maxmq_tpu.protocol.codec import FixedHeader

    remaining = 0
    shift = 0
    i = 1
    while True:
        if i >= len(raw):
            raise MalformedPacketError("truncated fixed header")
        b = raw[i]
        remaining |= (b & 0x7F) << shift
        i += 1
        if not b & 0x80:
            break
        shift += 7
    return FixedHeader.decode(raw[0], remaining), raw[i:]


def decode_case(case: dict) -> Packet:
    raw = bytes.fromhex(case["raw"])
    buf = bytearray(raw)
    packets = list(parse_stream(buf))
    assert packets, "fixed header did not frame"
    assert not buf, "leftover bytes after framing"
    fh, body = packets[0]
    return Packet.decode(fh, body, infer_version(case))


@pytest.mark.parametrize(
    "case", CASES, ids=[c.get("case", "?") for c in CASES])
def test_tpacket_case(case):
    if case["group"] == "encode":
        pytest.skip("encode-direction mutation case: the semantics "
                    "(optional-property shedding under the client max "
                    "packet size) are pinned by test_validate_cases."
                    "test_encode_under_drops_optional_properties; the "
                    "Go fixtures' exact bytes need the pre-mutation "
                    "struct, which the extractor does not carry")
    if case["fail_first"] == "ErrPacketTooLarge":
        # replayed through the framing limit, where the reference's
        # ReadPacket enforces it
        raw = bytes.fromhex(case["raw"])
        with pytest.raises(ProtocolError):
            list(parse_stream(bytearray(raw),
                              max_packet_size=len(raw) - 1))
        return
    rejected = case["fail_first"] or (
        case["expect"] or "").startswith("Err")
    if rejected:
        # the reference rejects these bytes (XxxDecode error, or a spec
        # violation its Validate step catches); ours must reject too —
        # at framing or at decode
        with pytest.raises((MalformedPacketError, ProtocolError,
                            ValueError)):
            fh, body = frame_lenient(bytes.fromhex(case["raw"]))
            Packet.decode(fh, body, infer_version(case))
        return
    packet = decode_case(case)
    assert packet.type == case["ptype"]
    if case["primary"]:
        packet.protocol_version = infer_version(case)
        wire = packet.encode()
        assert wire.hex() == case["raw"], (
            f"canonical re-encode mismatch for {case['case']}:\n"
            f"  want {case['raw']}\n  got  {wire.hex()}")


def test_corpus_size_and_coverage():
    """The corpus must cover every packet type and both directions."""
    types = {c["ptype"] for c in CASES}
    assert types == set(range(1, 16))
    assert sum(1 for c in CASES if c["fail_first"]) >= 40
    assert sum(1 for c in CASES if c["primary"]) >= 50


def test_every_reference_case_accounted():
    """All 174 reference corpus cases are either replayed as wire
    vectors here or ported as named validate-direction tests
    (tools/tpackets_accounting.py keeps the ledger)."""
    path = os.path.join(os.path.dirname(__file__), "fixtures",
                        "tpackets_accounting.json")
    with open(path, encoding="utf-8") as fh:
        acct = json.load(fh)
    assert len(acct) >= 174
    unaccounted = [k for k, v in acct.items()
                   if v["status"] == "UNACCOUNTED"]
    assert not unaccounted, unaccounted
    # ledger in sync with the replayed fixture
    wire = {c["case"] for c in CASES}
    ledger_wire = {k for k, v in acct.items() if v["status"] == "wire"}
    assert wire <= ledger_wire | {None}
    # every covered-by test actually exists
    import re as _re
    src = open(os.path.join(os.path.dirname(__file__),
                            "test_validate_cases.py")).read()
    for v in acct.values():
        if v["status"] == "covered-by" and "::" in v["by"]:
            name = v["by"].split("::")[1]
            assert _re.search(rf"def {name}\b", src), v["by"]
