"""Tests for the metrics server, the maxmq_mqtt_* Prometheus bridge, the
logging hook, and the $SYS HTTP stats listener.

Models internal/metrics/server_test.go (constructor validation, bad address,
start/stop, scrape) and internal/mqtt/logging_test.go (log output per hook
event) in the reference."""

from __future__ import annotations

import io
import json
import urllib.request

import pytest

from maxmq_tpu.broker import Broker, BrokerOptions, Capabilities
from maxmq_tpu.broker.listeners import HTTPStatsListener
from maxmq_tpu.hooks.logging import LoggingHook
from maxmq_tpu.metrics import (MetricsServer, Registry,
                               register_broker_metrics)
from maxmq_tpu.protocol.codec import FixedHeader, PacketType
from maxmq_tpu.protocol.packets import Packet, Subscription
from maxmq_tpu.utils.logger import Logger, set_severity_level


def scrape(port: int, path: str = "/metrics") -> tuple[int, str]:
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return r.status, r.read().decode()


class TestRegistry:
    def test_exposition_format(self):
        reg = Registry()
        reg.counter_func("test_total", "A counter.", lambda: 41)
        reg.gauge_func("test_now", "A gauge.", lambda: 1.5,
                       labels={"kind": "x"})
        text = reg.expose()
        assert "# HELP test_total A counter." in text
        assert "# TYPE test_total counter" in text
        assert "test_total 41" in text
        assert 'test_now{kind="x"} 1.5' in text

    def test_failing_metric_skipped(self):
        reg = Registry()

        def boom():
            raise RuntimeError

        reg.gauge_func("bad", "x", boom)
        reg.gauge_func("good", "x", lambda: 2)
        text = reg.expose()
        assert "good 2" in text
        assert not any(line.startswith("bad ")
                       for line in text.splitlines())


class TestMetricsServer:
    def test_invalid_address(self):
        with pytest.raises(ValueError):
            MetricsServer("no-port", Registry())

    def test_scrape_and_stop(self):
        reg = Registry()
        reg.gauge_func("up", "Server is up.", lambda: 1)
        srv = MetricsServer("127.0.0.1:0", reg)
        srv.start()
        try:
            status, text = scrape(srv.bound_port)
            assert status == 200
            assert "up 1" in text
            with pytest.raises(Exception):
                scrape(srv.bound_port, "/nope")
        finally:
            srv.stop()

    def test_profiling_endpoints(self):
        srv = MetricsServer("127.0.0.1:0", Registry(), profiling=True)
        srv.start()
        try:
            status, text = scrape(srv.bound_port, "/debug/pprof/threads")
            assert status == 200
            assert "Thread" in text or "File" in text
            status, _ = scrape(srv.bound_port, "/debug/pprof/heap")
            assert status == 200
        finally:
            srv.stop()

    def test_profiling_disabled_404(self):
        srv = MetricsServer("127.0.0.1:0", Registry(), profiling=False)
        srv.start()
        try:
            with pytest.raises(urllib.error.HTTPError):
                scrape(srv.bound_port, "/debug/pprof/threads")
        finally:
            srv.stop()


class TestBrokerBridge:
    def test_registers_mqtt_metrics(self):
        broker = Broker(BrokerOptions(capabilities=Capabilities()))
        broker.info.messages_received = 5
        broker.info.clients_connected = 2
        reg = Registry()
        register_broker_metrics(reg, broker)
        text = reg.expose()
        assert "maxmq_mqtt_messages_received 5" in text
        assert "maxmq_mqtt_clients_connected 2" in text
        # live read at scrape time, not registration time
        broker.info.messages_received = 9
        assert "maxmq_mqtt_messages_received 9" in reg.expose()


class _FakeClient:
    id = "cl1"
    listener = "t1"
    remote = "127.0.0.1:1"
    keepalive = 60
    inflight = ()


def _publish(topic="a/b", qos=0):
    p = Packet(fixed=FixedHeader(type=PacketType.PUBLISH, qos=qos))
    p.topic = topic
    p.payload = b"hi"
    return p


class TestLoggingHook:
    def _hook(self) -> tuple[LoggingHook, io.StringIO]:
        buf = io.StringIO()
        set_severity_level("trace")
        hook = LoggingHook(Logger(out=buf, fmt="json"))
        return hook, buf

    def _events(self, buf) -> list[dict]:
        return [json.loads(line) for line in buf.getvalue().splitlines()]

    def test_lifecycle_and_publish_events(self):
        hook, buf = self._hook()
        hook.on_started()
        hook.on_publish(_publish(), _FakeClient())
        hook.on_publish_dropped(_FakeClient(), _publish())
        hook.on_stopped()
        set_severity_level("info")
        events = self._events(buf)
        assert [e["message"] for e in events] == [
            "broker started", "received PUBLISH",
            "publish dropped (slow consumer)", "broker stopped"]
        assert events[1]["topic"] == "a/b"
        assert events[2]["level"] == "warn"

    def test_packet_read_is_modify_passthrough(self):
        hook, buf = self._hook()
        p = _publish()
        assert hook.on_packet_read(p, _FakeClient()) is p
        set_severity_level("info")
        event = self._events(buf)[0]
        assert event["type"] == "PUBLISH"
        assert event["level"] == "trace"

    def test_subscribe_events(self):
        hook, buf = self._hook()
        p = Packet(fixed=FixedHeader(type=PacketType.SUBSCRIBE))
        p.filters = [Subscription(filter="a/+", qos=1)]
        hook.on_subscribed(_FakeClient(), p, [1], [1])
        hook.on_unsubscribed(_FakeClient(), p)
        set_severity_level("info")
        events = self._events(buf)
        assert events[0]["filters"] == ["a/+"]
        assert events[1]["message"] == "client unsubscribed"


async def test_http_stats_listener():
    broker = Broker(BrokerOptions(capabilities=Capabilities(
        sys_topic_interval=0)))
    from maxmq_tpu.hooks import AllowHook
    broker.add_hook(AllowHook())
    listener = broker.add_listener(
        HTTPStatsListener("stats", "127.0.0.1:0", lambda: broker.info))
    await broker.serve()
    try:
        port = listener._server.sockets[0].getsockname()[1]
        import asyncio
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(b"GET /sys HTTP/1.1\r\nHost: x\r\n\r\n")
        await writer.drain()
        raw = await asyncio.wait_for(reader.read(), timeout=5)
        head, _, body = raw.partition(b"\r\n\r\n")
        assert b"200 OK" in head
        data = json.loads(body)
        assert data["version"] == broker.info.version
        assert "clients_connected" in data
    finally:
        await broker.close()


def test_matcher_metrics_series_render():
    """The ADR-007/008 matcher series (bypass, trie-route, RTT) appear
    in the exposition when a batcher-wrapped engine is attached."""
    from maxmq_tpu.matching.batcher import MicroBatcher
    from maxmq_tpu.matching.sig import SigEngine

    broker = Broker(BrokerOptions(
        capabilities=Capabilities(sys_topic_interval=0)))
    broker.topics.subscribe("m1", Subscription(filter="mx/+", qos=0))
    eng = SigEngine(broker.topics)
    mb = MicroBatcher(eng)
    broker.attach_matcher(mb)
    mb.bypasses = 3
    mb._device_rtt = 0.012   # seed the EWMA the property exposes
    eng.trie_routed = 5
    reg = Registry()
    register_broker_metrics(reg, broker)
    text = reg.expose()
    assert "maxmq_matcher_matches_total" in text
    assert "maxmq_matcher_bypassed_topics_total 3" in text
    assert "maxmq_matcher_device_rtt_seconds 0.012" in text
    assert "maxmq_matcher_trie_routed_total 5" in text


def test_kernel_width_metrics_render():
    """The ADR-010 dual-width kernel series reflect the LIVE plan at
    scrape time (groups/words by width, plane passes saved)."""
    from maxmq_tpu.matching.batcher import MicroBatcher
    from maxmq_tpu.matching.sig import SigEngine

    broker = Broker(BrokerOptions(
        capabilities=Capabilities(sys_topic_interval=0)))
    for i in range(3):
        broker.topics.subscribe(f"k{i}",
                                Subscription(filter=f"kw/{i}/#", qos=0))
    eng = SigEngine(broker.topics)
    broker.attach_matcher(MicroBatcher(eng))
    reg = Registry()
    register_broker_metrics(reg, broker)
    text = reg.expose()
    assert 'maxmq_matcher_kernel_groups{width="16"}' in text
    assert 'maxmq_matcher_kernel_groups{width="32"}' in text
    assert 'maxmq_matcher_kernel_words{width="16"}' in text
    assert "maxmq_matcher_kernel_plane_passes_saved_per_topic" in text
    if eng.kernel_plan is not None:     # pallas plan admitted the tables
        g16 = eng.kernel_plan["groups16"]
        assert f'maxmq_matcher_kernel_groups{{width="16"}} {g16}' in text
