"""ADR 024 units: the ``disk.*`` fault family, the fault-wrapping
backend shim, crash-point plumbing, and the hardened journal ladder.

The crash-day subprocess drills live in test_crashday.py; this file
exercises the machinery in-process — skip-field arming, the
FaultInjectingStore's injection points and delegation, commit-failure
classification, fsync poisoning (reopen-before-reprobe), the ENOSPC
rung (immediate trip + unconditional QoS0-rewrite shed + rung-down on
success), torn-tail truncation, the quarantine contract under random
garbage in every bucket, the move-aside-failure fix, the persisted
content-filter spec round trip, and the replica-flush crash point on
a live 2-node mesh (swapped kill_fn, no process dies)."""

import asyncio
import errno
import json
import os
import pathlib
import re
import sqlite3
import time
from contextlib import asynccontextmanager

import pytest

from maxmq_tpu import faults
from maxmq_tpu.broker import Broker, BrokerOptions, Capabilities, TCPListener
from maxmq_tpu.cluster import ClusterManager, PeerSpec
from maxmq_tpu.hooks import AllowHook
from maxmq_tpu.hooks.faultstore import (DiskFull, FaultInjectingStore,
                                        FsyncFailed, torn_tail)
from maxmq_tpu.hooks.journal import WriteBehindStore, classify_commit_failure
from maxmq_tpu.hooks.storage import (QUARANTINE_BUCKET, CorruptStoreError,
                                     SQLiteStore, StorageHook,
                                     SubscriptionRecord)
from maxmq_tpu.mqtt_client import MQTTClient


@pytest.fixture(autouse=True)
def clean_faults():
    faults.clear()
    kill_fn = faults.REGISTRY.kill_fn
    yield
    faults.clear()
    faults.REGISTRY.kill_fn = kill_fn


def wait_until(pred, timeout: float = 5.0, what: str = ""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"condition not reached in {timeout}s: {what}")


# ----------------------------------------------------------------------
# Fault registry: skip field + crash points
# ----------------------------------------------------------------------


def test_spec_skip_field_delays_fire_uncounted():
    kills = []
    faults.REGISTRY.kill_fn = lambda: kills.append(1)
    faults.arm_from_spec("crash.at#pre_fsync:kill:1:0:3")
    for _ in range(3):                   # three pass-through hits
        faults.crash_point("pre_fsync")
        assert not kills
        # near-misses are not trips
        assert faults.REGISTRY.fired.get("crash.at#pre_fsync", 0) == 0
    faults.crash_point("pre_fsync")      # the 4th hit fires
    assert kills == [1]
    assert faults.REGISTRY.fired["crash.at#pre_fsync"] == 1
    faults.crash_point("pre_fsync")      # count=1: spent
    assert kills == [1]


def test_every_crash_point_fires_and_other_points_pass():
    for point in faults.CRASH_POINTS:
        kills = []
        faults.REGISTRY.kill_fn = lambda k=kills: k.append(1)
        faults.arm(f"crash.at#{point}", "kill")
        for other in faults.CRASH_POINTS:
            if other != point:
                faults.crash_point(other)
        assert not kills, f"{point}: wrong point tripped"
        faults.crash_point(point)
        assert kills == [1], f"{point}: armed point did not fire"
        faults.clear()


def test_crash_point_registry_matches_call_sites():
    """Every CRASH_POINTS name must have a production call site and
    every call site must be registered — the two lists drift apart
    silently otherwise (an unregistered point can never be armed; a
    registered-but-never-called one gives false coverage)."""
    pkg = pathlib.Path(faults.__file__).parent
    called = set()
    for py in pkg.rglob("*.py"):
        if py.name == "faults.py":
            continue
        called |= set(re.findall(r'crash_point\(\s*"([a-z_]+)"',
                                 py.read_text()))
    assert called == set(faults.CRASH_POINTS)


# ----------------------------------------------------------------------
# Commit-failure classification
# ----------------------------------------------------------------------


def test_classify_commit_failure():
    assert classify_commit_failure(FsyncFailed()) == "fsync"
    assert classify_commit_failure(
        OSError(errno.ENOSPC, "no space")) == "enospc"
    assert classify_commit_failure(DiskFull()) == "enospc"
    assert classify_commit_failure(
        sqlite3.OperationalError("database or disk is full")) == "enospc"
    assert classify_commit_failure(
        OSError("fsync failed on journal")) == "fsync"
    assert classify_commit_failure(OSError(errno.EIO, "eio")) == "other"
    assert classify_commit_failure(ValueError("boom")) == "other"


# ----------------------------------------------------------------------
# FaultInjectingStore shim
# ----------------------------------------------------------------------


def test_faultstore_injects_and_delegates(tmp_path):
    inner = SQLiteStore(str(tmp_path / "s.db"))
    store = FaultInjectingStore(inner)

    faults.arm(faults.DISK_WRITE, "err", 1)
    with pytest.raises(OSError) as exc:
        store.put("b", "k", "v")
    assert exc.value.errno == errno.EIO
    assert store.get("b", "k") is None       # EIO fires BEFORE the write

    faults.arm(faults.DISK_ENOSPC, "err", 1)
    with pytest.raises(DiskFull):
        store.put("b", "k", "v")

    # fsync failure fires AFTER the inner op: the write may have landed
    # (flush-result-unknown is exactly the fsyncgate ambiguity)
    faults.arm(faults.DISK_FSYNC, "err", 1)
    with pytest.raises(FsyncFailed):
        store.put("b", "k", "v1")
    assert inner.get("b", "k") == "v1"

    faults.arm(faults.DISK_LATENCY, "hang", 1, delay_s=0.08)
    t0 = time.perf_counter()
    store.put("b", "k2", "v2")
    assert time.perf_counter() - t0 >= 0.08

    # delegation: reads, bulk reads, counters, reopen
    assert store.get("b", "k2") == "v2"
    assert store.all("b") == {"k": "v1", "k2": "v2"}
    assert store.corruptions == 0            # __getattr__ passthrough
    store.reopen()
    assert store.get("b", "k2") == "v2"

    faults.arm(faults.DISK_ENOSPC, "err", 1)
    with pytest.raises(DiskFull):
        store.apply_batch([("put", "b", "k3", "v3")])
    store.apply_batch([("put", "b", "k3", "v3")])
    assert store.get("b", "k3") == "v3"
    store.close()


def test_torn_tail_truncates(tmp_path):
    db = str(tmp_path / "t.db")
    inner = SQLiteStore(db)
    for i in range(50):
        inner.put("b", f"k{i}", "x" * 64)
    wal = db + "-wal"
    assert os.path.exists(wal)
    before = os.path.getsize(wal)
    cut = torn_tail(db, 128, target="wal")
    assert cut == 128
    assert os.path.getsize(wal) == before - 128
    inner.close()
    before_db = os.path.getsize(db)
    cut = torn_tail(db, 64, target="db")
    assert cut == 64 and os.path.getsize(db) == before_db - 64
    # a cut larger than the file empties it instead of raising
    assert torn_tail(db, 10**9, target="db") == before_db - 64
    assert os.path.getsize(db) == 0


# ----------------------------------------------------------------------
# Journal hardening: fsync poisoning + ENOSPC rung
# ----------------------------------------------------------------------


def _journal(tmp_path, name, **kw):
    kw.setdefault("policy", "always")
    kw.setdefault("backoff_s", 0.05)
    kw.setdefault("backoff_max_s", 0.1)
    return WriteBehindStore(
        FaultInjectingStore(SQLiteStore(str(tmp_path / name))), **kw)


def test_fsync_failure_poisons_then_reopens_and_replays(tmp_path):
    j = _journal(tmp_path, "fs.db")
    try:
        faults.arm(faults.DISK_FSYNC, "err", 1)
        j.put("b", "k", "v1")
        wait_until(lambda: j.fsync_failures == 1, what="fsync counted")
        # fsync class trips the breaker IMMEDIATELY — no 5-strike grace
        assert j.breaker_trips >= 1
        wait_until(lambda: j.breaker_recoveries >= 1 and j.commits >= 1,
                   what="reprobe recovered")
        # the reprobe reopened the poisoned connection BEFORE retrying
        assert j.backend_reopens == 1
        assert not j._poisoned
        assert j.flush(timeout=5.0)
        assert j.inner.get("b", "k") == "v1"     # parked op replayed
    finally:
        j.close()


def test_enospc_trips_immediately_and_clears_on_success(tmp_path):
    j = _journal(tmp_path, "eno.db")
    try:
        faults.arm(faults.DISK_ENOSPC, "err", 1)
        j.put("b", "k", "v1")
        wait_until(lambda: j.enospc_failures == 1, what="enospc counted")
        assert j.breaker_trips >= 1              # immediate trip
        wait_until(lambda: j.disk_full or j.commits >= 1,
                   what="disk_full observed or already recovered")
        wait_until(lambda: not j.disk_full and j.commits >= 1,
                   what="rung down on first successful commit")
        assert j.flush(timeout=5.0)
        assert j.inner.get("b", "k") == "v1"
    finally:
        j.close()


def test_enospc_shed_rung_sheds_qos0_rewrites_unconditionally(tmp_path):
    j = _journal(tmp_path, "shed.db")
    hook = StorageHook(j)

    class _Over:
        disk_full_sheds = 0

    class _Server:
        overload = _Over()

    class _Client:
        server = _Server()

    try:
        client = _Client()
        assert hook._shed_rewrite(client) is False   # healthy: no shed
        j.disk_full = True
        # full disk: shed regardless of watermark/overload state
        assert hook._shed_rewrite(client) is True
        assert client.server.overload.disk_full_sheds == 1
        j.disk_full = False
        assert hook._shed_rewrite(client) is False
    finally:
        j.close()


# ----------------------------------------------------------------------
# Quarantine property: random garbage in every bucket
# ----------------------------------------------------------------------


def test_restore_quarantines_random_garbage_exactly(tmp_path):
    import random
    rng = random.Random(240)
    store = SQLiteStore(str(tmp_path / "q.db"))
    junk_gens = (
        lambda: '{"torn": tru',                      # torn JSON
        lambda: "",                                  # empty record
        lambda: "[]",                                # wrong JSON shape
        lambda: '"just a string"',                   # wrong JSON shape
        lambda: "\x00" + "".join(chr(rng.randrange(32, 300))
                                 for _ in range(rng.randrange(1, 40))),
    )
    planted = []
    for bucket in ("clients", "subscriptions", "retained", "inflight"):
        for i in range(rng.randrange(3, 8)):
            key = f"junk-{bucket}-{i}"
            store.put(bucket, key, rng.choice(junk_gens)())
            planted.append((bucket, key))
    good = SubscriptionRecord(client_id="c1", filter="a/b", qos=1)
    store.put("subscriptions", "c1|a/b", good.to_json())

    hook = StorageHook(store)
    # restore must NEVER raise, whatever the garbage
    assert hook.stored_clients() == []
    subs = hook.stored_subscriptions()
    assert hook.stored_retained_messages() == []
    assert hook.stored_inflight_messages() == []

    assert [s.filter for s in subs] == ["a/b"]       # the good one lives
    assert hook.quarantined == len(planted)
    rows = store.all(QUARANTINE_BUCKET)
    assert len(rows) == len(planted)                 # one row per record
    for bucket, key in planted:
        assert f"{bucket}|{key}" in rows
        assert store.get(bucket, key) is None        # moved, not copied
    store.close()


# ----------------------------------------------------------------------
# Move-aside failure fix (satellite: the silenced except OSError)
# ----------------------------------------------------------------------


def test_recreate_aside_failure_counted_and_still_boots(tmp_path,
                                                        monkeypatch):
    db = str(tmp_path / "c.db")
    with open(db, "w") as f:
        f.write("not a sqlite file at all")

    def refuse_replace(src, dst):
        raise OSError(errno.EACCES, "injected: aside volume readonly")

    monkeypatch.setattr(os, "replace", refuse_replace)
    store = SQLiteStore(db)          # boot MUST still succeed
    assert store.corruptions == 1
    assert store.aside_failures == 1     # counted, not swallowed
    store.put("b", "k", "v")
    assert store.get("b", "k") == "v"
    store.close()
    # the damaged file was removed in place (forensic copy lost), so
    # no .corrupt-N sibling exists
    assert not [p for p in os.listdir(tmp_path) if ".corrupt-" in p]


# ----------------------------------------------------------------------
# Persisted content-filter specs (satellite: ?$expr= / ?$agg= restore)
# ----------------------------------------------------------------------


@asynccontextmanager
async def running_broker(db=None, **caps):
    """Broker with hooks attached BEFORE serve() — restore from the
    storage hook happens inside serve (server.py)."""
    caps.setdefault("sys_topic_interval", 0)
    b = Broker(BrokerOptions(capabilities=Capabilities(**caps)))
    b.add_hook(AllowHook())
    if db is not None:
        b.add_hook(StorageHook(SQLiteStore(db)))
    listener = b.add_listener(TCPListener("t1", "127.0.0.1:0"))
    await b.serve()
    b.test_port = listener._server.sockets[0].getsockname()[1]
    try:
        yield b
    finally:
        await b.close()


async def test_content_spec_survives_restart(tmp_path):
    db = str(tmp_path / "content.db")
    async with running_broker(db=db, content_filtering=True) as b1:
        c = MQTTClient(client_id="cf", clean_start=False)
        await c.connect("127.0.0.1", b1.test_port)
        assert await c.subscribe(
            ("s/t?$expr=payload.temp>30", 1),
            ("s/a?$agg=avg&$win=5s&$field=payload.v", 0),
            ("s/plain", 0)) == [1, 0, 0]
        sub = b1.content.get("cf", "s/t")
        assert sub.spec.source == "$expr=payload.temp>30"
        await c.disconnect()

    async with running_broker(db=db, content_filtering=True) as b2:
        sub = b2.content.get("cf", "s/t")
        assert sub is not None
        assert sub.spec.source == "$expr=payload.temp>30"
        agg = b2.content.get("cf", "s/a")
        assert agg is not None and agg.spec.agg == "avg"
        assert b2.content.get("cf", "s/plain") is None
        assert b2.content.active

        # and it FILTERS: resume the session, mismatching payload is
        # masked, matching one delivers
        c = MQTTClient(client_id="cf", clean_start=False)
        await c.connect("127.0.0.1", b2.test_port)
        assert c.connack.session_present
        pub = MQTTClient(client_id="p")
        await pub.connect("127.0.0.1", b2.test_port)
        await pub.publish("s/t", b'{"temp": 10}', qos=1, timeout=5.0)
        await pub.publish("s/t", b'{"temp": 99}', qos=1, timeout=5.0)
        m = await c.next_message(timeout=5.0)
        assert m.payload == b'{"temp": 99}'
        with pytest.raises(asyncio.TimeoutError):
            await c.next_message(timeout=0.3)
        await pub.close()
        await c.close()


async def test_unparseable_restored_spec_degrades_not_fails_boot(tmp_path):
    db = str(tmp_path / "badspec.db")
    store = SQLiteStore(db)
    rec = SubscriptionRecord(client_id="cf", filter="s/t", qos=1,
                             options="$expr=payload..broken>")
    store.put("subscriptions", "cf|s/t", rec.to_json())
    store.close()
    async with running_broker(db=db, content_filtering=True) as b:
        # boot (with restore inside serve) must not raise; the spec is
        # rejected loudly but the subscription itself still restored,
        # just unfiltered
        assert b.content.get("cf", "s/t") is None
        assert b.content.rejected_subscribes == 1
        assert b.info.subscriptions == 1


# ----------------------------------------------------------------------
# Replica-flush crash point on a live mesh (swapped kill_fn)
# ----------------------------------------------------------------------


async def _make_node() -> Broker:
    b = Broker(BrokerOptions(capabilities=Capabilities(
        sys_topic_interval=0)))
    b.add_hook(AllowHook())
    listener = b.add_listener(TCPListener("t", "127.0.0.1:0"))
    await b.serve()
    b.test_port = listener._server.sockets[0].getsockname()[1]
    return b


async def test_replica_flush_crash_point_trips_on_mesh():
    kills = []
    faults.REGISTRY.kill_fn = lambda: kills.append(1)
    a, b = await _make_node(), await _make_node()
    mgr_a = ClusterManager(a, "a", [PeerSpec("b", "127.0.0.1",
                                             b.test_port)],
                           keepalive=0.5, backoff_initial_s=0.05)
    mgr_b = ClusterManager(b, "b", [PeerSpec("a", "127.0.0.1",
                                             a.test_port)],
                           keepalive=0.5, backoff_initial_s=0.05)
    a.attach_cluster(mgr_a)
    b.attach_cluster(mgr_b)
    await mgr_a.start()
    await mgr_b.start()
    try:
        faults.arm("crash.at#replica_flush", "kill")
        c = MQTTClient(client_id="rc", clean_start=False)
        await c.connect("127.0.0.1", a.test_port)
        await c.subscribe(("r/t", 1))        # dirties the session entry
        deadline = time.monotonic() + 5.0
        while not kills and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        assert kills, "replica_flush crash point never reached"
        await c.close()
    finally:
        faults.clear()
        await a.close()
        await b.close()
