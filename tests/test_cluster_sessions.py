"""Federated sessions e2e suite (ADR 016): session replication across
bridge peers, epoch-fenced takeover (dual-CONNECT split brain resolves
to exactly one live session, loser disconnected with SessionTakenOver),
parked-inflight transfer with zero PUBACKed loss, cluster-wide $share
exactly-once across a 3-node line, degradation under the
cluster.session_sync / cluster.takeover fault sites (CONNECT never
wedges), plus the incremental minimal-cover and ShareLedger units and
the SIGKILL node-kill failover harness (subprocess brokers in the
test_storage_recovery.py style)."""

import asyncio
import json
import os
import random
import signal
import socket
import sqlite3
import subprocess
import sys
import time
from contextlib import asynccontextmanager

import pytest

from maxmq_tpu import faults
from maxmq_tpu.broker import Broker, BrokerOptions, Capabilities, TCPListener
from maxmq_tpu.cluster import (ClusterManager, IncrementalCover, PeerSpec,
                               SessionEntry, ShareLedger, minimal_cover)
from maxmq_tpu.hooks import AllowHook
from maxmq_tpu.mqtt_client import MQTTClient
from maxmq_tpu.protocol import codes


@pytest.fixture(autouse=True)
def clean_faults():
    faults.clear()
    yield
    faults.clear()


async def make_node(**caps) -> Broker:
    caps.setdefault("sys_topic_interval", 0)
    b = Broker(BrokerOptions(capabilities=Capabilities(**caps)))
    b.add_hook(AllowHook())
    listener = b.add_listener(TCPListener("t", "127.0.0.1:0"))
    await b.serve()
    b.test_port = listener._server.sockets[0].getsockname()[1]
    return b


@asynccontextmanager
async def cluster(topology: dict[str, list[str]], **kw):
    """One broker + session-federated manager per topology entry."""
    kw.setdefault("keepalive", 0.5)
    kw.setdefault("backoff_initial_s", 0.05)
    kw.setdefault("backoff_max_s", 0.5)
    kw.setdefault("session_sync", "always")
    kw.setdefault("session_sync_timeout_ms", 500)
    kw.setdefault("session_takeover_timeout_ms", 500)
    brokers: dict[str, Broker] = {}
    managers: dict[str, ClusterManager] = {}
    for name in topology:
        brokers[name] = await make_node()
    for name, peers in topology.items():
        specs = [PeerSpec(p, "127.0.0.1", brokers[p].test_port)
                 for p in peers]
        mgr = ClusterManager(brokers[name], name, specs, **kw)
        brokers[name].attach_cluster(mgr)
        managers[name] = mgr
        await mgr.start()
    try:
        yield brokers, managers
    finally:
        for b in brokers.values():
            await b.close()


async def wait_for(predicate, timeout: float = 10.0, what: str = ""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        await asyncio.sleep(0.02)
    raise AssertionError(f"condition not reached in {timeout}s: {what}")


async def connect(broker: Broker, client_id: str, **kw) -> MQTTClient:
    c = MQTTClient(client_id=client_id, **kw)
    await c.connect("127.0.0.1", broker.test_port)
    return c


# ----------------------------------------------------------------------
# Units: incremental cover, share ledger, fencing tokens, entry codec
# ----------------------------------------------------------------------


def _random_filter(rng) -> str:
    levels = []
    for _ in range(rng.randint(1, 4)):
        levels.append(rng.choice(["a", "b", "c", "+", "x"]))
    if rng.random() < 0.3:
        levels.append("#")
    return "/".join(levels)


def test_incremental_cover_equivalence_randomized():
    """The incremental cover must equal minimal_cover() after any
    add/remove sequence — 200 random ops across duplicate, subsuming,
    and disjoint filter shapes."""
    rng = random.Random(16)
    cov = IncrementalCover()
    live: list[str] = []
    for _ in range(200):
        if live and rng.random() < 0.45:
            f = live.pop(rng.randrange(len(live)))
            cov.remove(f)
        else:
            f = _random_filter(rng)
            live.append(f)
            cov.add(f)
        assert cov.cover == minimal_cover(live), \
            (sorted(live), sorted(cov.cover))
    for f in list(live):
        cov.remove(f)
    assert cov.cover == set() and cov.refs == {}


def test_incremental_cover_re_expose_collapses():
    """Removing a broad cover member re-exposes what it subsumed, and
    re-exposed filters that subsume each other still collapse."""
    cov = IncrementalCover(["#", "a/#", "a/b", "c"])
    assert cov.cover == {"#"}
    cov.remove("#")
    assert cov.cover == {"a/#", "c"}        # a/b re-hid behind a/#
    cov.remove("a/#")
    assert cov.cover == {"a/b", "c"}


def test_share_ledger_ownership_rules():
    led = ShareLedger("B")
    key = ("g", "$share/g/s/t")
    assert led.owns(key)                    # nobody claims: local wins
    led.set_local(key, 1)
    assert led.owns(key)
    led.set_member("A", key, 2)
    assert not led.owns(key)                # lowest member id owns
    led.set_member("A", key, 0)
    assert led.owns(key)
    led.replace_member("C", {key: 1, ("g2", "$share/g2/x"): 1})
    assert led.owns(key)                    # B < C
    led.set_local(key, 0)
    assert not led.owns(key)                # only C holds members now
    led.drop_member("C")
    assert led.owns(key) and led.group_count == 0


def test_fencing_token_ordering_and_entry_roundtrip():
    a = SessionEntry("c", "A", session_epoch=3, boot_epoch=100)
    b = SessionEntry("c", "B", session_epoch=4, boot_epoch=50)
    assert b.token > a.token                # session_epoch dominates
    c = SessionEntry("c", "C", session_epoch=4, boot_epoch=60)
    assert c.token > b.token                # boot_epoch breaks the tie
    d = SessionEntry("c", "D", session_epoch=4, boot_epoch=60)
    assert d.token > c.token                # node id breaks exact ties
    e = SessionEntry("cl", "A", 7, 9, expiry=30, expiry_set=True,
                     protocol_version=5, connected=True,
                     subs=[["t/#", 1, 0, 0, 0, 0]],
                     shares=[["g", "$share/g/t/#"]], digest=(2, 5))
    back = SessionEntry.from_meta_json(e.meta_json())
    assert (back.cid, back.owner, back.token) == ("cl", "A", e.token)
    assert back.subs == e.subs and back.shares == e.shares
    assert back.digest == (2, 5) and back.expiry == 30 and back.expiry_set


# ----------------------------------------------------------------------
# Replication + takeover (in-process 2-node)
# ----------------------------------------------------------------------


async def test_session_replicates_and_journals():
    """Session metadata (subs, $share, epoch) reaches the peer's ledger
    and its write-behind journal shortly after SUBSCRIBE."""
    async with cluster({"A": ["B"], "B": ["A"]}) as (brokers, mgrs):
        from maxmq_tpu.hooks.storage import MemoryStore, StorageHook
        hook = StorageHook(MemoryStore())
        brokers["B"].add_hook(hook)
        brokers["B"]._storage_hook = hook
        c = await connect(brokers["A"], "repl", version=5,
                          clean_start=False, session_expiry=300)
        await c.subscribe(("t/#", 1), ("$share/g/s/t", 0))
        sB = mgrs["B"].sessions
        await wait_for(lambda: "repl" in sB.ledger
                       and len(sB.ledger["repl"].subs) == 2,
                       what="entry replicated to B")
        entry = sB.ledger["repl"]
        assert entry.owner == "A" and entry.connected
        assert ["g", "$share/g/s/t"] in entry.shares
        # journaled through the storage hook (ADR 014 path)
        raw = hook.store.get("cluster_sessions", "repl")
        assert raw is not None
        assert json.loads(raw)["owner"] == "A"
        # the cluster-wide share ledger learned A's membership
        assert not sB.owns_share("g", "$share/g/s/t")   # A < B
        await c.disconnect()
        await wait_for(lambda: not sB.ledger["repl"].connected,
                       what="disconnect replicated")


async def test_dual_connect_split_brain_resolves_to_one_session():
    """Dual CONNECT for one client id: the later claim's higher fencing
    token wins, the losing node's client is disconnected with v5
    SessionTakenOver, state (subs + inflight digest) transfers, and
    session epochs strictly increase across repeated takeovers."""
    async with cluster({"A": ["B"], "B": ["A"]}) as (brokers, mgrs):
        A, B = brokers["A"], brokers["B"]
        sA, sB = mgrs["A"].sessions, mgrs["B"].sessions
        c1 = await connect(A, "dual", version=5, clean_start=False,
                           session_expiry=3600)
        await c1.subscribe(("t/#", 1))
        await wait_for(lambda: "dual" in sB.ledger, what="replicated")
        epochs = [sB.ledger["dual"].session_epoch]

        c2 = await connect(B, "dual", version=5, clean_start=False,
                           session_expiry=3600)
        assert c2.session_present is True
        await wait_for(lambda: c1.disconnect_packet is not None,
                       what="loser disconnected")
        assert (c1.disconnect_packet.reason_code
                == codes.ErrSessionTakenOver.value)
        await wait_for(lambda: A.clients.get("dual") is None,
                       what="A dropped its replica")
        # exactly one live session: a publish at A routes to B's client
        assert sA.ledger["dual"].owner == "B"
        epochs.append(sB.ledger["dual"].session_epoch)
        pub = await connect(A, "pub-a")
        await pub.publish("t/x", b"after-takeover", qos=1)
        msg = await c2.next_message(timeout=5)
        assert msg.payload == b"after-takeover"
        assert sB.takeovers == 1 and sA.sessions_lost == 1
        assert sB.state_transfers == 1

        # take it back: epochs keep strictly increasing
        c3 = await connect(A, "dual", version=5, clean_start=False,
                           session_expiry=3600)
        assert c3.session_present is True
        await wait_for(lambda: sB.ledger["dual"].owner == "A",
                       what="ownership returned to A")
        epochs.append(sA.ledger["dual"].session_epoch)
        assert epochs[0] < epochs[1] < epochs[2], epochs
        for c in (c2, c3, pub):
            await c.close()


async def test_offline_inflight_transfers_on_takeover():
    """QoS1 messages parked for an OFFLINE persistent session on A are
    redelivered after the client reconnects to B — the parked window
    moves with the session (state pull from the live prior owner)."""
    async with cluster({"A": ["B"], "B": ["A"]}) as (brokers, mgrs):
        A, B = brokers["A"], brokers["B"]
        sub = await connect(A, "park", version=5, clean_start=False,
                            session_expiry=3600)
        await sub.subscribe(("park/#", 1))
        await sub.disconnect()
        pub = await connect(A, "park-pub")
        sent = set()
        for i in range(20):
            payload = f"p-{i}".encode()
            await pub.publish("park/q", payload, qos=1)
            sent.add(payload)
        sub2 = await connect(B, "park", version=5, clean_start=False,
                             session_expiry=3600)
        assert sub2.session_present is True
        got = set()
        while len(got) < len(sent):
            m = await sub2.next_message(timeout=5)
            got.add(m.payload)
        assert got == sent
        assert mgrs["B"].sessions.digest_mismatches == 0
        await sub2.close()
        await pub.close()


async def test_clean_start_purges_replicated_state():
    """A clean-start CONNECT at a peer purges the replicated session
    instead of resuming it: session-present=0 everywhere after."""
    async with cluster({"A": ["B"], "B": ["A"]}) as (brokers, mgrs):
        c1 = await connect(brokers["A"], "cs", version=5,
                           clean_start=False, session_expiry=3600)
        await c1.subscribe(("t/#", 1))
        sB = mgrs["B"].sessions
        await wait_for(lambda: "cs" in sB.ledger
                       and sB.ledger["cs"].subs, what="replicated")
        await c1.disconnect()
        c2 = await connect(brokers["B"], "cs", version=5,
                           clean_start=True)
        assert c2.session_present in (False, None)
        await wait_for(lambda: not sB.ledger["cs"].subs
                       and not mgrs["A"].sessions.ledger["cs"].subs,
                       what="replicated subs purged cluster-wide")
        await c2.close()


# ----------------------------------------------------------------------
# Cluster-wide $share (3-node line)
# ----------------------------------------------------------------------


async def test_cluster_wide_share_exactly_once_on_line():
    """A $share group with one member on each node of a 3-node line
    receives every matching publish exactly once CLUSTER-WIDE — the
    ledger's lowest-live-member-node rule (pin mode; the ADR-018
    weighted rotation has its own suite in test_partition.py), with
    membership replicated transitively across the middle node."""
    line = {"A": ["B"], "B": ["A", "C"], "C": ["B"]}
    async with cluster(line, session_sync="batched",
                       share_balance="pin") as (brokers, mgrs):
        members = {}
        for name in ("A", "B", "C"):
            m = await connect(brokers[name], f"sh-{name}")
            await m.subscribe(("$share/g/s/t", 0))
            members[name] = m
        key = ("g", "$share/g/s/t")
        for name in ("A", "B", "C"):
            await wait_for(
                lambda n=name: len(mgrs[n].routes.shares.members_for(key))
                == 3, what=f"{name} sees all 3 member nodes")
        # publish at each node; exactly one member must receive each
        pubs = {n: await connect(brokers[n], f"shpub-{n}")
                for n in ("A", "B", "C")}
        n_msgs = 0
        for origin in ("A", "B", "C"):
            for i in range(4):
                await pubs[origin].publish("s/t", f"{origin}-{i}".encode())
                n_msgs += 1
        got: list[tuple[str, bytes]] = []

        async def drain(name, cli):
            while True:
                try:
                    m = await cli.next_message(timeout=1.0)
                except asyncio.TimeoutError:
                    return
                got.append((name, m.payload))

        await asyncio.gather(*(drain(n, c) for n, c in members.items()))
        payloads = [p for _, p in got]
        assert len(payloads) == n_msgs, \
            f"expected {n_msgs} exactly-once deliveries, saw {len(payloads)}"
        assert len(set(payloads)) == n_msgs      # no duplicates either
        # ownership is deterministic: every delivery landed on ONE node
        assert len({n for n, _ in got}) == 1
        for c in list(members.values()) + list(pubs.values()):
            await c.close()


async def test_share_pool_and_cluster_ledgers_compose():
    """$share ownership on a box is ONE ledger per node (ADR 021: the
    worker pool rides the federation's ShareLedger — the ADR-005 bus
    hook with its private worker ledger is gone). A member id from a
    foreign mesh segment (a pool worker's node id, a peer box) claims
    ownership through the same set_member surface the session
    federation feeds, and the select-time guard honors it."""
    async with cluster({"A": ["B"], "B": ["A"]}, session_sync="batched",
                       share_balance="pin") as (brokers, mgrs):
        A = brokers["A"]
        member = await connect(A, "pc-member")
        await member.subscribe(("$share/g/s/t", 0))
        key = ("g", "$share/g/s/t")
        pub = await connect(A, "pc-pub")

        # a pool-worker node id with live members pins below "A" ->
        # this node does not own the pick; no local delivery
        mgrs["A"].routes.shares.set_member("0.w0", key, 1)
        await pub.publish("s/t", b"worker-owned-elsewhere")
        with pytest.raises(asyncio.TimeoutError):
            await member.next_message(timeout=0.4)

        # the worker ceded (all its members offline) -> local delivery
        mgrs["A"].routes.shares.set_member("0.w0", key, 0)
        await pub.publish("s/t", b"owned-here")
        m = await member.next_message(timeout=5)
        assert m.payload == b"owned-here"
        await member.close()
        await pub.close()


# ----------------------------------------------------------------------
# Degradation: fault sites, lag, and the never-wedge contract
# ----------------------------------------------------------------------


async def test_session_sync_fault_degrades_connect_never_wedges():
    """With cluster.session_sync dropping every replication send from
    A, B's ledger never learns the session — the client's reconnect at
    B degrades to a FRESH session (counted) and the CONNACK still
    arrives promptly. QoS acks at A degrade through the bounded
    replication barrier instead of wedging the publisher."""
    async with cluster({"A": ["B"], "B": ["A"]},
                       session_sync_timeout_ms=200) as (brokers, mgrs):
        sA, sB = mgrs["A"].sessions, mgrs["B"].sessions
        await wait_for(lambda: mgrs["A"].links_up == 1, what="link up")
        faults.arm("cluster.session_sync#B", "drop", count=-1)
        c1 = await connect(brokers["A"], "deg", version=5,
                           clean_start=False, session_expiry=3600)
        await c1.subscribe(("d/#", 1))
        # ack-coupled publish completes within the degrade bound
        pub = await connect(brokers["A"], "deg-pub")
        t0 = time.monotonic()
        await pub.publish("d/x", b"m", qos=1, timeout=5.0)
        assert time.monotonic() - t0 < 2.0
        await wait_for(lambda: sA.sync_faults > 0, what="fault counted")
        assert "deg" not in sB.ledger
        await c1.disconnect()
        t0 = time.monotonic()
        c2 = await connect(brokers["B"], "deg", version=5,
                           clean_start=False, session_expiry=3600)
        assert time.monotonic() - t0 < 3.0      # CONNECT never wedges
        assert c2.session_present in (False, None)  # fresh + counted loss
        assert sA.sync_degraded + sA.sync_timeouts > 0
        await c2.close()
        await pub.close()


async def test_takeover_fault_degrades_to_fresh_session():
    """cluster.takeover drop mode: the handoff path is unusable — the
    reconnect still completes, degraded to a fresh session, counted in
    takeovers_degraded (visible in $SYS/metrics)."""
    async with cluster({"A": ["B"], "B": ["A"]}) as (brokers, mgrs):
        c1 = await connect(brokers["A"], "tof", version=5,
                           clean_start=False, session_expiry=3600)
        await c1.subscribe(("t/#", 1))
        sB = mgrs["B"].sessions
        await wait_for(lambda: "tof" in sB.ledger, what="replicated")
        faults.arm("cluster.takeover#A", "drop", count=1)
        c2 = await connect(brokers["B"], "tof", version=5,
                           clean_start=False, session_expiry=3600)
        assert sB.takeovers_degraded == 1
        assert sB.takeovers == 0    # degraded, not ALSO successful
        # the fresh session still owns the id cluster-wide afterwards
        await wait_for(lambda: sB.ledger["tof"].owner == "B",
                       what="claim still broadcast")
        await c2.close()


async def test_barrier_ignores_unacked_broadcasts():
    """An unacked broadcast (claim/purge/state) bumps the global seq
    but must NOT become a barrier target: a healthy publisher's next
    QoS1 ack would otherwise stall the full sync timeout waiting for a
    seq no peer will ever ack (regression)."""
    async with cluster({"A": ["B"], "B": ["A"]}) as (brokers, mgrs):
        sA = mgrs["A"].sessions
        c = await connect(brokers["A"], "barr", version=5,
                          clean_start=False, session_expiry=300)
        await c.subscribe(("b/#", 1))
        await wait_for(
            lambda: sA._peer_acked.get("B", 0)
            >= sA._peer_ack_target.get("B", 0) > 0,
            what="replication acked")
        # trailing seq now belongs to a never-acked broadcast
        sA._broadcast("claim", {"cid": "ghost", "se": 1, "be": 0,
                                "purge": 0, "pull": 0})
        assert sA._next_seq > sA._peer_acked.get("B", 0)
        fut = sA.sync_barrier(asyncio.get_running_loop())
        assert fut is None      # nothing ack-requested is outstanding
        await c.close()


async def test_refused_send_heals_with_live_link_resync():
    """A replication send refused while the link stays UP schedules a
    per-link resync, so the peer's replica converges instead of keeping
    a permanent gap masked by later high-watermark acks (regression)."""
    async with cluster({"A": ["B"], "B": ["A"]}) as (brokers, mgrs):
        sA, sB = mgrs["A"].sessions, mgrs["B"].sessions
        c = await connect(brokers["A"], "gap", version=5,
                          clean_start=False, session_expiry=300)
        await c.subscribe(("g/1", 1))
        await wait_for(lambda: "gap" in sB.ledger
                       and sB.ledger["gap"].subs, what="replicated")
        link = mgrs["A"].links["B"]
        real = link.send_session
        refused = {"n": 0}

        def flaky(topic, payload, on_ack=None):
            if refused["n"] == 0:
                refused["n"] += 1
                return False        # one refused enqueue, link still up
            return real(topic, payload, on_ack=on_ack)

        link.send_session = flaky
        await c.subscribe(("g/2", 1))   # this update's send is refused
        await wait_for(lambda: sA.sync_resyncs >= 1, what="resync ran")
        await wait_for(
            lambda: any(r[0] == "g/2" for r in sB.ledger["gap"].subs),
            what="gap healed by the live-link resync")
        assert sA.sync_send_failures >= 1
        await c.close()


async def test_malformed_replicated_row_degrades_not_fails_connect():
    """A malformed subscription row in the handoff state (buggy/older
    peer) is skipped and counted — the takeover still installs the good
    rows and the CONNECT completes with session-present=1, never an
    exception out of the handshake (regression)."""
    async with cluster({"A": ["B"], "B": ["A"]}) as (brokers, mgrs):
        sB = mgrs["B"].sessions
        c1 = await connect(brokers["A"], "mal", version=5,
                           clean_start=False, session_expiry=3600)
        await c1.subscribe(("ok/#", 1), ("oops/#", 1))
        await wait_for(lambda: "mal" in sB.ledger
                       and len(sB.ledger["mal"].subs) == 2,
                       what="replicated")
        await c1.disconnect()
        # corrupt what A will ship on the pull leg: identifier becomes
        # a non-numeric string, so the install's int() would raise
        offline = brokers["A"].clients.get("mal")
        offline.subscriptions["oops/#"].identifier = "x"
        c2 = await connect(brokers["B"], "mal", version=5,
                           clean_start=False, session_expiry=3600)
        assert c2.session_present is True
        live = brokers["B"].clients.get("mal")
        assert "ok/#" in live.subscriptions     # good row installed
        assert "oops/#" not in live.subscriptions
        assert sB.restore_errors >= 1
        await c2.close()


async def test_purged_session_recreates_above_tombstone_epoch():
    """A session re-created after its purge claims ABOVE the purged
    epoch (tombstone), so a peer that missed the fire-and-forget purge
    broadcast cannot fence the new incarnation with its stale replica
    (regression)."""
    async with cluster({"A": ["B"], "B": ["A"]}) as (brokers, mgrs):
        sA, sB = mgrs["A"].sessions, mgrs["B"].sessions
        c1 = await connect(brokers["A"], "tmb", version=5,
                           clean_start=False, session_expiry=3600)
        await c1.subscribe(("t/#", 1))
        for _ in range(3):      # pump the epoch well above 1
            await c1.disconnect()
            c1 = await connect(brokers["A"], "tmb", version=5,
                               clean_start=False, session_expiry=3600)
        high = sA.ledger["tmb"].session_epoch
        assert high >= 4
        await wait_for(lambda: "tmb" in sB.ledger
                       and sB.ledger["tmb"].session_epoch == high,
                       what="high epoch replicated")
        # B misses the purge: every replication send from A drops
        faults.arm("cluster.session_sync#B", "drop", count=-1)
        c2 = await connect(brokers["A"], "tmb", version=5,
                           clean_start=True)   # purges the session
        faults.clear()
        # the re-created session continues above the tombstone...
        assert sA.ledger["tmb"].session_epoch > high
        await c2.subscribe(("t/new", 1))
        # ...so B's stale replica is superseded, not fencing it
        await wait_for(
            lambda: sB.ledger["tmb"].session_epoch
            == sA.ledger["tmb"].session_epoch,
            what="stale replica superseded despite missed purge")
        await c2.close()


async def test_sessions_sys_tree_and_metrics_registered():
    async with cluster({"A": ["B"], "B": ["A"]}) as (brokers, mgrs):
        c = await connect(brokers["A"], "sysc", version=5,
                          clean_start=False, session_expiry=60)
        await c.subscribe(("x/#", 0))
        brokers["A"].publish_sys_topics()
        ret = brokers["A"].topics.retained_get(
            "$SYS/broker/cluster/sessions/local")
        assert ret is not None and int(ret.payload) >= 1
        from maxmq_tpu.metrics import Registry, register_broker_metrics
        reg = Registry()
        register_broker_metrics(reg, brokers["A"])
        page = reg.expose()
        assert "maxmq_cluster_session_ledger" in page
        assert "maxmq_cluster_session_takeovers_total" in page
        assert "maxmq_cluster_session_sync_degraded_total" in page
        await c.close()


# ----------------------------------------------------------------------
# Node-kill failover harness (subprocess brokers, SIGKILL, no grace)
# ----------------------------------------------------------------------

BROKER_SCRIPT = """
import asyncio, os
from maxmq_tpu.bootstrap import new_logger_from_config, run_server
from maxmq_tpu.utils.config import load_config
conf = load_config(path=None, env=os.environ)
asyncio.run(run_server(conf, new_logger_from_config(conf)))
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_node(tmp_path, node: str, db: str, port: int,
                peers: str) -> subprocess.Popen:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env.update(
        MAXMQ_MQTT_TCP_ADDRESS=f"127.0.0.1:{port}",
        MAXMQ_STORAGE_BACKEND="sqlite",
        MAXMQ_STORAGE_PATH=db,
        MAXMQ_STORAGE_SYNC="always",
        MAXMQ_CLUSTER_NODE_ID=node,
        MAXMQ_CLUSTER_PEERS=peers,
        MAXMQ_CLUSTER_SESSION_SYNC="always",
        MAXMQ_CLUSTER_LINK_KEEPALIVE="0.5",
        MAXMQ_METRICS_ENABLED="false",
        MAXMQ_MATCHER="trie",
        MAXMQ_MQTT_SYS_TOPIC_INTERVAL="0",
        MAXMQ_LOG_LEVEL="error",
        JAX_PLATFORMS="cpu",
    )
    env.pop("MAXMQ_FAULTS", None)
    return subprocess.Popen([sys.executable, "-c", BROKER_SCRIPT],
                            env=env, cwd=str(tmp_path))


async def _wait_ready(port: int, proc: subprocess.Popen,
                      timeout: float = 30.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        assert proc.poll() is None, \
            f"broker subprocess died at boot (rc={proc.returncode})"
        try:
            _r, w = await asyncio.open_connection("127.0.0.1", port)
            w.close()
            return
        except OSError:
            await asyncio.sleep(0.05)
    raise AssertionError("broker subprocess never started accepting")


async def _wait_linked(port: int, peer: str, timeout: float = 20.0) -> None:
    """Wait until the node at ``port`` holds ``peer``'s retained route
    snapshot — proof the bridge from ``peer`` delivered (link is up)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        probe = MQTTClient(client_id=f"probe-{peer}-{port}")
        try:
            await probe.connect("127.0.0.1", port)
            await probe.subscribe((f"$cluster/routes/{peer}", 0))
            try:
                await probe.next_message(timeout=1.0)
                return
            except asyncio.TimeoutError:
                pass
        except OSError:
            pass
        finally:
            await probe.close()
        await asyncio.sleep(0.1)
    raise AssertionError(f"peer {peer} never linked to :{port}")


def _kill(proc: subprocess.Popen) -> None:
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=10)


def _read_kv(db_path: str, bucket: str) -> dict:
    conn = sqlite3.connect(db_path)
    try:
        rows = conn.execute(
            "SELECT key, value FROM kv WHERE bucket=?", (bucket,)).fetchall()
        return dict(rows)
    finally:
        conn.close()


async def test_node_kill_failover_zero_pubacked_loss(tmp_path):
    """SIGKILL node A mid-QoS1-stream (storage_sync=always +
    cluster_session_sync=always): the client reconnects to node B with
    session-present=1, the subscription survives, every PUBACKed
    message is redelivered (zero loss), and B's replicated ledger shows
    the takeover with a strictly-increased session epoch."""
    dbA = str(tmp_path / "a.db")
    dbB = str(tmp_path / "b.db")
    pA, pB = _free_port(), _free_port()
    procA = _spawn_node(tmp_path, "A", dbA, pA, f"B@127.0.0.1:{pB}")
    procB = _spawn_node(tmp_path, "B", dbB, pB, f"A@127.0.0.1:{pA}")
    acked: list[int] = []
    try:
        await _wait_ready(pA, procA)
        await _wait_ready(pB, procB)
        # both directions of the bridge must be live before the stream:
        # the replication barrier only covers CONNECTED peers
        await _wait_linked(pB, "A")
        await _wait_linked(pA, "B")

        sub = MQTTClient(client_id="fo-sub", clean_start=False)
        await sub.connect("127.0.0.1", pA)
        await sub.subscribe(("fo/#", 1))
        await sub.disconnect()

        pub = MQTTClient(client_id="fo-pub")
        await pub.connect("127.0.0.1", pA)

        async def stream():
            for i in range(5000):
                try:
                    await pub.publish("fo/q", f"m-{i}".encode(), qos=1,
                                      timeout=5.0)
                except Exception:
                    return              # broker died mid-flight
                acked.append(i)

        streamer = asyncio.ensure_future(stream())
        while len(acked) < 15 and not streamer.done():
            await asyncio.sleep(0.005)
        _kill(procA)                    # mid-stream, zero grace
        await streamer
        assert len(acked) >= 15
    finally:
        if procA.poll() is None:
            _kill(procA)

    try:
        sub2 = MQTTClient(client_id="fo-sub", clean_start=False)
        await sub2.connect("127.0.0.1", pB)
        # the replicated session resumed on B: session-present=1
        assert sub2.connack.session_present is True
        got: set[bytes] = set()
        while True:
            try:
                m = await sub2.next_message(timeout=3.0)
            except asyncio.TimeoutError:
                break
            got.add(m.payload)
        missing = {f"m-{i}".encode() for i in acked} - got
        assert not missing, \
            f"{len(missing)} PUBACKed messages lost: {sorted(missing)[:5]}"
        # subscription survived: a fresh publish through B delivers
        # without any re-SUBSCRIBE
        pub2 = MQTTClient(client_id="fo-pub2")
        await pub2.connect("127.0.0.1", pB)
        await pub2.publish("fo/alive", b"post-failover", qos=1)
        m = await sub2.next_message(timeout=5.0)
        assert m.payload == b"post-failover"
        await pub2.disconnect()
        await sub2.disconnect()
    finally:
        if procB.poll() is None:
            _kill(procB)
    # B journaled the takeover: it owns the session at a higher epoch
    sess = _read_kv(dbB, "cluster_sessions")
    assert "fo-sub" in sess
    rec = json.loads(sess["fo-sub"])
    assert rec["owner"] == "B" and rec["se"] >= 2


test_node_kill_failover_zero_pubacked_loss._async_timeout = 180
