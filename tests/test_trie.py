"""Semantic tests for the CPU reference matcher: wildcard matching per MQTT
spec 4.7, `$share` handling, retained scans, merge rules, aliases."""

import pytest

from maxmq_tpu.matching import (
    TopicAliases,
    TopicIndex,
    parse_share,
    valid_filter,
    valid_topic_name,
)
from maxmq_tpu.protocol import FixedHeader, Packet, PacketType as PT, Subscription


def sub(index, client, filt, qos=0, ident=0):
    return index.subscribe(client, Subscription(filter=filt, qos=qos,
                                                identifier=ident))


def match_clients(index, topic):
    return sorted(index.subscribers(topic).subscriptions)


# ---------------------------------------------------------------------------
# Filter validation
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("filt,ok", [
    ("a/b/c", True), ("#", True), ("+", True), ("a/+/c", True), ("a/#", True),
    ("/", True), ("a//b", True), ("+/+/+", True), ("/finance", True),
    ("", False), ("a/#/b", False), ("a#", False), ("#/a", False),
    ("a/b+", False), ("+a", False), ("a+/b", False),
    ("$share/g/t/#", True), ("$share/g/+", True),
    ("$share//t", False), ("$share/g", False), ("$share/g/", False),
    ("$share/g+/t", False), ("$share/g#/t", False),
])
def test_valid_filter(filt, ok):
    assert valid_filter(filt) == ok


def test_valid_filter_feature_gates():
    assert not valid_filter("$share/g/t", shared_allowed=False)
    assert not valid_filter("a/+", wildcards_allowed=False)
    assert valid_filter("a/b", shared_allowed=False, wildcards_allowed=False)


def test_valid_topic_name():
    assert valid_topic_name("a/b")
    assert not valid_topic_name("")
    assert not valid_topic_name("a/+")
    assert not valid_topic_name("a/#")


def test_parse_share():
    assert parse_share("$share/g/a/b") == ("g", "a/b")
    assert parse_share("a/b") == ("", "a/b")
    assert parse_share("$share/g") == ("g", "")


# ---------------------------------------------------------------------------
# Wildcard matching semantics
# ---------------------------------------------------------------------------

def test_exact_match():
    idx = TopicIndex()
    sub(idx, "c1", "a/b/c")
    sub(idx, "c2", "a/b")
    assert match_clients(idx, "a/b/c") == ["c1"]
    assert match_clients(idx, "a/b") == ["c2"]
    assert match_clients(idx, "a") == []
    assert match_clients(idx, "a/b/c/d") == []


def test_plus_wildcard():
    idx = TopicIndex()
    sub(idx, "c1", "sport/+/player1")
    sub(idx, "c2", "sport/+")
    sub(idx, "c3", "+")
    assert match_clients(idx, "sport/tennis/player1") == ["c1"]
    assert match_clients(idx, "sport/tennis") == ["c2"]
    assert match_clients(idx, "sport") == ["c3"]
    # '+' matches empty levels too: 'sport/' is ['sport','']
    assert match_clients(idx, "sport/") == ["c2"]


def test_hash_wildcard_matches_parent():
    # spec 4.7.1.2: "sport/tennis/player1/#" matches the parent itself
    idx = TopicIndex()
    sub(idx, "c1", "sport/tennis/player1/#")
    assert match_clients(idx, "sport/tennis/player1") == ["c1"]
    assert match_clients(idx, "sport/tennis/player1/ranking") == ["c1"]
    assert match_clients(idx, "sport/tennis/player1/score/wimbledon") == ["c1"]
    assert match_clients(idx, "sport/tennis/player2") == []


def test_root_hash_matches_all_but_dollar():
    idx = TopicIndex()
    sub(idx, "c1", "#")
    assert match_clients(idx, "a") == ["c1"]
    assert match_clients(idx, "a/b/c") == ["c1"]
    assert match_clients(idx, "/") == ["c1"]
    # [MQTT-4.7.2-1]: no match on $-topics
    assert match_clients(idx, "$SYS/broker/load") == []


def test_root_plus_excludes_dollar():
    idx = TopicIndex()
    sub(idx, "c1", "+/monitor/Clients")
    sub(idx, "c2", "$SYS/monitor/+")
    assert match_clients(idx, "$SYS/monitor/Clients") == ["c2"]
    sub(idx, "c3", "$SYS/#")
    assert match_clients(idx, "$SYS/monitor/Clients") == ["c2", "c3"]


def test_empty_level_handling():
    idx = TopicIndex()
    sub(idx, "c1", "/finance")
    assert match_clients(idx, "/finance") == ["c1"]
    sub(idx, "c2", "+/+")
    sub(idx, "c3", "/+")
    assert sorted(match_clients(idx, "/finance")) == ["c1", "c2", "c3"]


def test_overlapping_filters_merge_max_qos_and_ids():
    idx = TopicIndex()
    idx.subscribe("c1", Subscription(filter="a/+", qos=0, identifier=7))
    idx.subscribe("c1", Subscription(filter="a/b", qos=2, identifier=9))
    result = idx.subscribers("a/b")
    assert len(result.subscriptions) == 1
    merged = result.subscriptions["c1"]
    assert merged.qos == 2
    assert merged.identifiers in ({"a/+": 7, "a/b": 9},)


def test_unsubscribe_and_trim():
    idx = TopicIndex()
    sub(idx, "c1", "a/b/c")
    assert idx.subscription_count == 1
    assert idx.unsubscribe("c1", "a/b/c") is True
    assert idx.unsubscribe("c1", "a/b/c") is False
    assert match_clients(idx, "a/b/c") == []
    assert idx.subscription_count == 0
    # trie fully trimmed
    assert not idx._root.children


def test_resubscribe_not_new():
    idx = TopicIndex()
    assert sub(idx, "c1", "a/b") is True
    assert sub(idx, "c1", "a/b", qos=1) is False
    assert idx.subscription_count == 1
    assert idx.subscribers("a/b").subscriptions["c1"].qos == 1


# ---------------------------------------------------------------------------
# Shared subscriptions
# ---------------------------------------------------------------------------

def test_shared_subscription_grouping():
    idx = TopicIndex()
    sub(idx, "c1", "$share/g1/t/+")
    sub(idx, "c2", "$share/g1/t/+")
    sub(idx, "c3", "$share/g2/t/a")
    sub(idx, "c4", "t/a")
    res = idx.subscribers("t/a")
    assert sorted(res.subscriptions) == ["c4"]
    assert ("g1", "$share/g1/t/+") in res.shared
    assert sorted(res.shared[("g1", "$share/g1/t/+")]) == ["c1", "c2"]
    assert sorted(res.shared[("g2", "$share/g2/t/a")]) == ["c3"]


def test_shared_round_robin_selection():
    idx = TopicIndex()
    sub(idx, "c1", "$share/g/t")
    sub(idx, "c2", "$share/g/t")
    sub(idx, "c3", "$share/g/t")
    res = idx.subscribers("t")
    cands = res.shared[("g", "$share/g/t")]
    picks = [idx.select_shared("g", "$share/g/t", cands)[0] for _ in range(6)]
    assert picks == ["c1", "c2", "c3", "c1", "c2", "c3"]


def test_shared_selection_skips_dead():
    idx = TopicIndex()
    sub(idx, "c1", "$share/g/t")
    sub(idx, "c2", "$share/g/t")
    cands = idx.subscribers("t").shared[("g", "$share/g/t")]
    pick = idx.select_shared("g", "$share/g/t", cands,
                             alive=lambda c: c == "c2")
    assert pick[0] == "c2"
    assert idx.select_shared("g", "$share/g/t", cands,
                             alive=lambda c: False) is None


def test_shared_unsubscribe():
    idx = TopicIndex()
    sub(idx, "c1", "$share/g/t")
    assert idx.unsubscribe("c1", "$share/g/t") is True
    assert idx.subscribers("t").shared == {}
    assert not idx._root.children


# ---------------------------------------------------------------------------
# Retained messages
# ---------------------------------------------------------------------------

def ret(topic, payload=b"x", created=0.0):
    return Packet(fixed=FixedHeader(type=PT.PUBLISH, retain=True), topic=topic,
                  payload=payload, created=created)


def test_retain_add_replace_clear():
    idx = TopicIndex()
    assert idx.retain(ret("a/b")) == 1
    assert idx.retained_count == 1
    assert idx.retain(ret("a/b", b"y")) == 0
    assert idx.retained_count == 1
    assert idx.retain(ret("a/b", b"")) == -1
    assert idx.retained_count == 0
    assert idx.retain(ret("nope", b"")) == 0  # clearing nothing
    assert not idx._root.children


def test_retained_scan_wildcards():
    idx = TopicIndex()
    idx.retain(ret("a/b", created=1))
    idx.retain(ret("a/c", created=2))
    idx.retain(ret("a/b/c", created=3))
    idx.retain(ret("x", created=4))
    assert [p.topic for p in idx.retained_for("a/b")] == ["a/b"]
    assert sorted(p.topic for p in idx.retained_for("a/+")) == ["a/b", "a/c"]
    assert [p.topic for p in idx.retained_for("a/#")] == ["a/b", "a/c", "a/b/c"]
    assert len(idx.retained_for("#")) == 4
    assert idx.retained_for("zzz") == []


def test_retained_hash_matches_parent_level():
    idx = TopicIndex()
    idx.retain(ret("a"))
    idx.retain(ret("a/b"))
    got = sorted(p.topic for p in idx.retained_for("a/#"))
    assert got == ["a", "a/b"]


def test_retained_scan_excludes_dollar_for_wildcards():
    idx = TopicIndex()
    idx.retain(ret("$SYS/uptime"))
    idx.retain(ret("normal"))
    assert [p.topic for p in idx.retained_for("#")] == ["normal"]
    assert [p.topic for p in idx.retained_for("+")] == ["normal"]
    assert [p.topic for p in idx.retained_for("$SYS/uptime")] == ["$SYS/uptime"]
    assert [p.topic for p in idx.retained_for("$SYS/#")] == ["$SYS/uptime"]


def test_all_subscriptions_enumeration():
    idx = TopicIndex()
    sub(idx, "c1", "a/b", qos=1)
    sub(idx, "c2", "$share/g/x")
    entries = sorted(idx.all_subscriptions())
    assert ("a/b", "c1") == entries[0][:2]
    shared = [e for e in entries if e[3] == "g"]
    assert len(shared) == 1 and shared[0][1] == "c2"


# ---------------------------------------------------------------------------
# Topic aliases
# ---------------------------------------------------------------------------

def test_inbound_alias_learning():
    al = TopicAliases(maximum=5)
    assert al.resolve_inbound("t/1", 3) == "t/1"     # learn
    assert al.resolve_inbound("", 3) == "t/1"        # use
    assert al.resolve_inbound("", 4) is None         # unknown alias
    assert al.resolve_inbound("x", 0) is None        # alias 0 invalid
    assert al.resolve_inbound("x", 9) is None        # over maximum
    assert al.resolve_inbound("plain", None) == "plain"


def test_outbound_alias_assignment():
    al = TopicAliases(maximum=2)
    assert al.assign_outbound("t/1") == (1, True)
    assert al.assign_outbound("t/1") == (1, False)
    assert al.assign_outbound("t/2") == (2, True)
    assert al.assign_outbound("t/3") == (0, False)  # exhausted
    assert TopicAliases(0).assign_outbound("t") == (0, False)
