"""Tests for the C++ native host runtime: tokenizer parity with the Python
path and frame-scanner parity with both the Python reference scanner and the
real packet codec."""

from __future__ import annotations

import random

import numpy as np
import pytest

from maxmq_tpu import native
from maxmq_tpu.matching.topics import tokenize_topics
from maxmq_tpu.protocol.codec import FixedHeader, PacketType
from maxmq_tpu.protocol.packets import Packet, Subscription

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library not built")


def rand_topics(rng: random.Random, n: int) -> list[str]:
    segs = ["sensor", "data", "", "Ω-unit", "dev1", "$SYS", "a" * 60, "+",
            "#", "x"]
    out = []
    for _ in range(n):
        depth = rng.randint(1, 12)
        out.append("/".join(rng.choice(segs) for _ in range(depth)))
    out += ["", "/", "//", "$", "$SYS/broker/load", "no-slash"]
    return out


class TestTokenizer:
    def test_parity_with_python(self):
        rng = random.Random(5)
        vocab = {}
        for i, level in enumerate(["sensor", "data", "dev1", "$SYS", "",
                                   "Ω-unit", "x"]):
            vocab[level] = i + 1
        nv = native.NativeVocab(vocab)
        assert len(nv) == len(vocab)
        topics = rand_topics(rng, 500)
        for max_levels in (1, 4, 16):
            t1, l1, d1 = tokenize_topics(vocab, topics, max_levels)
            t2, l2, d2 = nv.tokenize(topics, max_levels)
            assert np.array_equal(l1, l2)
            assert np.array_equal(d1, d2)
            assert np.array_equal(t1, t2)

    def test_unknown_levels_get_unk(self):
        nv = native.NativeVocab({"a": 1})
        toks, lengths, dollar = nv.tokenize(["a/zzz/a"], 4)
        assert toks.tolist() == [[1, 0, 1, -1]]
        assert lengths.tolist() == [3]
        assert not dollar[0]

    def test_overflow_marks_minus_one(self):
        nv = native.NativeVocab({})
        toks, lengths, _ = nv.tokenize(["a/b/c/d/e"], 3)
        assert lengths.tolist() == [-1]
        assert (toks == -1).all()

    def test_engine_uses_native_tokenizer(self):
        from maxmq_tpu.matching import TopicIndex
        from maxmq_tpu.matching.dense import DenseEngine
        idx = TopicIndex()
        idx.subscribe("c1", Subscription(filter="a/+"))
        engine = DenseEngine(idx)
        assert sorted(engine.subscribers("a/b").subscriptions) == ["c1"]
        assert engine.tables.__dict__.get("_native_vocab") is not None


def encode(ptype: int, payload: bytes = b"") -> bytes:
    out = bytearray([ptype << 4])
    rem = len(payload)
    while True:
        b = rem % 128
        rem //= 128
        out.append(b | (0x80 if rem else 0))
        if not rem:
            break
    return bytes(out) + payload


class TestFrameScanner:
    def test_complete_frames(self):
        data = (encode(PacketType.PINGREQ) +
                encode(PacketType.PUBLISH, b"x" * 300) +
                encode(PacketType.DISCONNECT))
        frames, consumed = native.scan_frames(data)
        assert consumed == len(data)
        assert [data[s] >> 4 for s, _ in frames] == [
            PacketType.PINGREQ, PacketType.PUBLISH, PacketType.DISCONNECT]
        assert frames == native.scan_frames_py(data)[0]

    def test_partial_tail_frame(self):
        full = encode(PacketType.PUBLISH, b"y" * 50)
        data = encode(PacketType.PINGREQ) + full[:20]
        frames, consumed = native.scan_frames(data)
        assert len(frames) == 1
        assert consumed == 2  # scanning stopped at the truncated PUBLISH
        assert native.scan_frames_py(data) == (frames, consumed)

    def test_truncated_varint_waits(self):
        data = bytes([PacketType.PUBLISH << 4, 0x80, 0x80])
        frames, consumed = native.scan_frames(data)
        assert frames == [] and consumed == 0

    def test_malformed_type_zero(self):
        with pytest.raises(native.MalformedFrame):
            native.scan_frames(b"\x00\x00")
        with pytest.raises(native.MalformedFrame):
            native.scan_frames_py(b"\x00\x00")

    def test_malformed_overlong_varint(self):
        data = bytes([PacketType.PUBLISH << 4, 0x80, 0x80, 0x80, 0x80, 0x80])
        with pytest.raises(native.MalformedFrame):
            native.scan_frames(data)
        with pytest.raises(native.MalformedFrame):
            native.scan_frames_py(data)

    def test_parity_against_real_codec_stream(self):
        """Scan a stream of real encoded packets; boundaries must slice
        each packet exactly."""
        packets = []
        p = Packet(fixed=FixedHeader(type=PacketType.PUBLISH, qos=1))
        p.topic, p.packet_id, p.payload = "a/b", 7, b"hello"
        packets.append(p.encode())
        s = Packet(fixed=FixedHeader(type=PacketType.SUBSCRIBE),
                   protocol_version=5)
        s.packet_id = 9
        s.filters = [Subscription(filter="x/#", qos=1)]
        packets.append(s.encode())
        packets.append(Packet(
            fixed=FixedHeader(type=PacketType.PINGRESP)).encode())
        data = b"".join(packets)
        frames, consumed = native.scan_frames(data)
        assert consumed == len(data)
        assert [data[a:b] for a, b in frames] == packets

    def test_random_fuzz_parity(self):
        rng = random.Random(11)
        for _ in range(50):
            data = bytes(rng.randrange(256)
                         for _ in range(rng.randint(0, 200)))
            try:
                got = native.scan_frames(data)
            except native.MalformedFrame:
                with pytest.raises(native.MalformedFrame):
                    native.scan_frames_py(data)
                continue
            assert got == native.scan_frames_py(data)


def test_tokenize_sig_parity_with_python():
    """mq_tokenize_sig must produce exactly tokenize_compact's encoding and
    the same host-exact hits as the numpy path."""
    import numpy as np
    import pytest

    from maxmq_tpu import native
    from maxmq_tpu.matching import TopicIndex
    from maxmq_tpu.matching.sig import (compile_sig, host_exact_rows,
                                        prepare_batch, tokenize_compact)
    from maxmq_tpu.protocol import Subscription

    if not native.available():
        pytest.skip("native library unavailable")

    idx = TopicIndex()
    idx.subscribe("c1", Subscription(filter="a/b/c"))
    idx.subscribe("c2", Subscription(filter="a/b"))
    idx.subscribe("c3", Subscription(filter="x/+/z"))
    idx.subscribe("c4", Subscription(filter="deep/#"))
    tables = compile_sig(idx)
    topics = ["a/b/c", "a/b", "x/q/z", "$SYS/x", "unknown/levels/here",
              "a//b", "", "deep", "t/" + "/".join(["v"] * 80)]

    toks_py, lens_py, toks32, lengths = tokenize_compact(tables, topics)
    hr_py = host_exact_rows(tables, toks32, lengths)
    from maxmq_tpu.matching.sig import host_plus_rows
    host_plus_rows(tables, toks_py, lengths, lens_py < 0, into=hr_py)

    toks_n, lens_n, hr_n = prepare_batch(tables, topics)
    assert toks_n.dtype == toks_py.dtype
    assert np.array_equal(toks_n, toks_py)
    assert np.array_equal(lens_n, lens_py)
    for a, b in zip(hr_n, hr_py):
        assert np.array_equal(a, b)


def _decode_mod():
    from maxmq_tpu.native import decode_module
    mod = decode_module()
    if mod is None:
        pytest.skip("maxmq_decode extension unavailable")
    return mod


def test_get_chain_params_round_trip():
    """_get_chain_params reports the live values so finally blocks can
    restore exactly what was in effect (ADVICE r5 #3)."""
    mod = _decode_mod()
    if not hasattr(mod, "_get_chain_params"):
        pytest.skip("getter unavailable (stale extension)")
    saved = mod._get_chain_params()
    try:
        mod._set_chain_params(17, 3, 2)
        assert mod._get_chain_params() == (17, 3, 2)
    finally:
        mod._set_chain_params(*saved)
    assert mod._get_chain_params() == saved


def test_prewarm_bases_continues_past_oversized_rows():
    """One row too fat for the 3/4 slot-map budget must not abort the
    whole prewarm sweep (ADVICE r5 #4): smaller later rows still get
    their anchors. Exercised at test scale by shrinking the budget so
    the FIRST fat row exceeds it while a later, thinner fat row fits."""
    from maxmq_tpu.matching import TopicIndex
    from maxmq_tpu.matching.sig import _native_decode, compile_sig

    mod = _decode_mod()
    for attr in ("_set_slot_map_cap", "_get_slot_map_cap",
                 "_slot_map_stats", "prewarm_bases"):
        if not hasattr(mod, attr):
            pytest.skip(f"{attr} unavailable (stale extension)")

    idx = TopicIndex()
    # row order follows subscription order: the 40-entry row first
    for i in range(40):
        idx.subscribe(f"big{i}", Subscription(filter="pb/big/#", qos=1))
    for i in range(20):
        idx.subscribe(f"small{i}", Subscription(filter="pb/small/#",
                                                qos=1))
    tables = compile_sig(idx)
    nd = _native_decode(tables)
    assert nd is not None
    _mod, cap = nd
    from maxmq_tpu.native import chain_params_in_effect
    saved_chain = chain_params_in_effect(mod)
    saved_cap = mod._get_slot_map_cap()
    try:
        mod._set_chain_params(16, 1, 1)     # both rows anchor-eligible
        # budget 48: 3/4 bar = 36 — the 40-entry row exceeds it, the
        # 20-entry row fits; the old code ended the sweep at the fat row
        mod._set_slot_map_cap(48)
        r = mod.prewarm_bases(cap, 0, 1000)
        rows_mapped, entries = mod._slot_map_stats(cap)
        assert r == len(tables.row_entries), r
        assert rows_mapped == 1, (rows_mapped, entries)
        assert entries == 20, entries
    finally:
        mod._set_slot_map_cap(saved_cap)
        mod._set_chain_params(*saved_chain)
