"""Differential codec validation against the independent spec decoder.

The reference validates its codec against a foreign implementation — the
Eclipse Paho client in its system tests (/root/reference/tests/system/
mqtt_test.go:35-253) and the vendored engine's Paho interop-suite claim.
No second MQTT implementation is installable in this image, so the
strongest available substitute is ``native/maxmq_refdecode.cpp``: a
decoder-only re-derivation of the OASIS MQTT specs in C++, sharing zero
code, tables, or constants with ``maxmq_tpu/protocol/``. This suite
decodes every wire case through BOTH decoders and requires agreement:

* both reject, or
* both accept with byte-identical canonical output (the ``canon``
  format defined in maxmq_refdecode.cpp's header comment).

Three passes: the full tpackets conformance corpus; randomized
well-formed packets produced by the production ENCODER (so encoder bugs
surface as refdecoder rejections); and random byte mutations of both
(so verdict disagreements on near-valid input surface).
"""

import ctypes
import json
import os
import random
import subprocess

import pytest

from maxmq_tpu.protocol.codec import FixedHeader, MalformedPacketError
from maxmq_tpu.protocol.packets import (
    Packet,
    ProtocolError,
    Subscription,
    Will,
)
from maxmq_tpu.protocol.properties import Properties

NATIVE_DIR = os.environ.get("MAXMQ_NATIVE_DIR") or os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
SO = os.path.join(NATIVE_DIR, "maxmq_refdecode.so")

if not os.path.exists(SO) and os.path.exists(
        os.path.join(NATIVE_DIR, "Makefile")):
    _build = subprocess.run(
        ["make", "-C", NATIVE_DIR, "-s", "maxmq_refdecode.so"],
        check=False, capture_output=True, timeout=120)
    if not os.path.exists(SO):
        # the gate must FAIL, not silently skip, when the sources are
        # present but won't build — a skipped differential suite looks
        # green while validating nothing
        raise RuntimeError("maxmq_refdecode.so build failed:\n"
                           + _build.stderr.decode()[-2000:])

pytestmark = pytest.mark.skipif(
    not os.path.exists(SO), reason="no native sources in this install")


def _lib():
    lib = ctypes.CDLL(SO)
    lib.mq_ref_decode.restype = ctypes.c_int64
    lib.mq_ref_decode.argtypes = [
        ctypes.c_uint8, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_int32, ctypes.c_char_p, ctypes.c_int64]
    return lib


LIB = _lib() if os.path.exists(SO) else None
_OUT = ctypes.create_string_buffer(1 << 20)


def ref_decode(first_byte: int, remaining: int, body: bytes,
               proto_ver: int) -> str | None:
    """Canonical text from the independent decoder, or None on reject."""
    n = LIB.mq_ref_decode(first_byte, remaining, body, len(body),
                          proto_ver, _OUT, len(_OUT))
    assert n != -2, "refdecode output buffer too small"
    return None if n < 0 else _OUT.raw[:n].decode()


# --------------------------------------------------------------------------
# Production-side canonicalizer (mirrors the contract in
# maxmq_refdecode.cpp — built from the DECODED Packet, so any structural
# disagreement between the decoders breaks the string comparison)
# --------------------------------------------------------------------------

def _hx(data) -> str:
    if isinstance(data, str):
        data = data.encode()
    return bytes(data).hex()


def _canon_props(p: Properties, prefix: str = "") -> str:
    """Ascending-property-id emission; empty strings/bytes = absent."""
    out = []

    def kv(pid, v):
        out.append(f"{prefix}p.{pid}={v}\n")

    if p.payload_format is not None:
        kv(1, p.payload_format)
    if p.message_expiry is not None:
        kv(2, p.message_expiry)
    if p.content_type:
        kv(3, _hx(p.content_type))
    if p.response_topic:
        kv(8, _hx(p.response_topic))
    if p.correlation_data:
        kv(9, _hx(p.correlation_data))
    for sid in p.subscription_ids:
        kv(11, sid)
    if p.session_expiry is not None:
        kv(17, p.session_expiry)
    if p.assigned_client_id:
        kv(18, _hx(p.assigned_client_id))
    if p.server_keep_alive is not None:
        kv(19, p.server_keep_alive)
    if p.auth_method:
        kv(21, _hx(p.auth_method))
    if p.auth_data:
        kv(22, _hx(p.auth_data))
    if p.request_problem_info is not None:
        kv(23, p.request_problem_info)
    if p.will_delay is not None:
        kv(24, p.will_delay)
    if p.request_response_info is not None:
        kv(25, p.request_response_info)
    if p.response_info:
        kv(26, _hx(p.response_info))
    if p.server_reference:
        kv(28, _hx(p.server_reference))
    if p.reason_string:
        kv(31, _hx(p.reason_string))
    if p.receive_maximum is not None:
        kv(33, p.receive_maximum)
    if p.topic_alias_max is not None:
        kv(34, p.topic_alias_max)
    if p.topic_alias is not None:
        kv(35, p.topic_alias)
    if p.maximum_qos is not None:
        kv(36, p.maximum_qos)
    if p.retain_available is not None:
        kv(37, p.retain_available)
    for k, v in p.user_properties:
        out.append(f"{prefix}p.38={_hx(k)},{_hx(v)}\n")
    if p.maximum_packet_size is not None:
        kv(39, p.maximum_packet_size)
    if p.wildcard_sub_available is not None:
        kv(40, p.wildcard_sub_available)
    if p.sub_id_available is not None:
        kv(41, p.sub_id_available)
    if p.shared_sub_available is not None:
        kv(42, p.shared_sub_available)
    return "".join(out)


def canon_packet(pk: Packet) -> str:  # qa: complex
    t = pk.fixed.type
    out = [f"t={t}\n"]
    if t == 3:
        out.append(f"dup={int(pk.fixed.dup)}\n")
        out.append(f"qos={pk.fixed.qos}\n")
        out.append(f"retain={int(pk.fixed.retain)}\n")
    if t == 1:
        out.append(f"v={pk.protocol_version}\n")
        out.append(f"clean={int(pk.clean_start)}\n")
        out.append(f"ka={pk.keepalive}\n")
        out.append(_canon_props(pk.properties))
        out.append(f"cid={_hx(pk.client_id)}\n")
        if pk.will is not None:
            out.append("w=1\n")
            out.append(f"w.qos={pk.will.qos}\n")
            out.append(f"w.retain={int(pk.will.retain)}\n")
            out.append(_canon_props(pk.will.properties, "w."))
            out.append(f"w.topic={_hx(pk.will.topic)}\n")
            out.append(f"w.payload={_hx(pk.will.payload)}\n")
        out.append(f"uf={int(pk.username_flag)}\n")
        if pk.username_flag:
            out.append(f"un={_hx(pk.username)}\n")
        out.append(f"pf={int(pk.password_flag)}\n")
        if pk.password_flag:
            out.append(f"pw={_hx(pk.password)}\n")
    elif t == 2:
        out.append(f"sp={int(pk.session_present)}\n")
        out.append(f"rc={pk.reason_code}\n")
        out.append(_canon_props(pk.properties))
    elif t == 3:
        out.append(f"topic={_hx(pk.topic)}\n")
        out.append(f"pid={pk.packet_id}\n")
        out.append(_canon_props(pk.properties))
        out.append(f"pl={_hx(pk.payload)}\n")
    elif t in (4, 5, 6, 7):
        out.append(f"pid={pk.packet_id}\n")
        out.append(f"rc={pk.reason_code}\n")
        out.append(_canon_props(pk.properties))
    elif t == 8:
        out.append(f"pid={pk.packet_id}\n")
        out.append(_canon_props(pk.properties))
        for s in pk.filters:
            out.append(f"f={_hx(s.filter)},{s.qos},{int(s.no_local)},"
                       f"{int(s.retain_as_published)},{s.retain_handling}\n")
    elif t == 9:
        out.append(f"pid={pk.packet_id}\n")
        out.append(_canon_props(pk.properties))
        out.append(f"rcs={_hx(bytes(pk.reason_codes))}\n")
    elif t == 10:
        out.append(f"pid={pk.packet_id}\n")
        out.append(_canon_props(pk.properties))
        for s in pk.filters:
            out.append(f"f={_hx(s.filter)}\n")
    elif t == 11:
        out.append(f"pid={pk.packet_id}\n")
        if pk.v5:
            out.append(_canon_props(pk.properties))
            out.append(f"rcs={_hx(bytes(pk.reason_codes))}\n")
    elif t in (12, 13):
        pass
    elif t in (14, 15):
        out.append(f"rc={pk.reason_code}\n")
        out.append(_canon_props(pk.properties))
    return "".join(out)


# --------------------------------------------------------------------------
# Framing + the differential comparison itself
# --------------------------------------------------------------------------

def frame(raw: bytes):
    """(first_byte, remaining, body). The body may be SHORTER than
    remaining (the corpus's truncated Mal* fixtures; both decoders must
    reject) but never longer: parse_stream slices the body to exactly
    `remaining` before Packet.decode ever sees it, so a longer slice
    would fuzz a state the transport cannot produce."""
    if not raw:
        raise MalformedPacketError("empty")
    remaining = 0
    shift = 0
    i = 1
    while True:
        if i >= len(raw):
            raise MalformedPacketError("truncated fixed header")
        if i > 4:
            raise MalformedPacketError("fixed header varint too long")
        b = raw[i]
        remaining |= (b & 0x7F) << shift
        i += 1
        if not b & 0x80:
            break
        shift += 7
    return raw[0], remaining, raw[i:i + remaining]


def prod_decode(first_byte: int, remaining: int, body: bytes,
                proto_ver: int) -> str | None:
    """Canonical text from the production codec, or None on reject."""
    try:
        fh = FixedHeader.decode(first_byte, remaining)
        pk = Packet.decode(fh, body, proto_ver)
    except (MalformedPacketError, ProtocolError):
        return None
    return canon_packet(pk)


def compare(raw: bytes, proto_ver: int, label: str) -> None:
    try:
        fb, remaining, body = frame(raw)
    except MalformedPacketError:
        return  # unframeable for both by construction
    got_prod = prod_decode(fb, remaining, body, proto_ver)
    got_ref = ref_decode(fb, remaining, body, proto_ver)
    if got_prod is None or got_ref is None:
        assert got_prod == got_ref, (
            f"{label}: verdict disagreement on {raw.hex()!r} v{proto_ver}: "
            f"production={'reject' if got_prod is None else 'ACCEPT'} "
            f"refdecode={'reject' if got_ref is None else 'ACCEPT'}\n"
            f"accepted form:\n{got_prod or got_ref}")
    else:
        assert got_prod == got_ref, (
            f"{label}: canonical disagreement on {raw.hex()!r} "
            f"v{proto_ver}:\n-- production --\n{got_prod}\n"
            f"-- refdecode --\n{got_ref}")


# --------------------------------------------------------------------------
# Pass 1: conformance corpus
# --------------------------------------------------------------------------

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures",
                        "tpackets.json")
with open(FIXTURES, encoding="utf-8") as fh:
    CASES = [c for c in json.load(fh) if c["ptype"] != 0]


def infer_version(case: dict) -> int:
    if case["protocol_version"]:
        return case["protocol_version"]
    name = case["case"] + case.get("desc", "")
    if "Mqtt5" in name or "mqtt v5" in name or "mqtt 5" in name:
        return 5
    if "Mqtt31" in name and "Mqtt311" not in name:
        return 3
    return 4


@pytest.mark.parametrize(
    "case", CASES, ids=[c.get("case", "?") for c in CASES])
def test_differential_corpus(case):
    compare(bytes.fromhex(case["raw"]), infer_version(case),
            case.get("case", "?"))


# --------------------------------------------------------------------------
# Pass 2: randomized well-formed packets via the production encoder
# --------------------------------------------------------------------------

def _rand_str(rng, lo=0, hi=24) -> str:
    n = rng.randint(lo, hi)
    return "".join(rng.choice("abcdefgh/+#$ é中") for _ in range(n))


def _rand_props(rng, v5: bool) -> Properties:
    p = Properties()
    if not v5:
        return p
    if rng.random() < 0.3:
        p.message_expiry = rng.randint(0, 2**32 - 1)
    if rng.random() < 0.3:
        p.content_type = _rand_str(rng, 1)
    if rng.random() < 0.3:
        p.response_topic = _rand_str(rng, 1)
    if rng.random() < 0.3:
        p.correlation_data = rng.randbytes(rng.randint(1, 16))
    if rng.random() < 0.3:
        p.payload_format = rng.randint(0, 1)
    if rng.random() < 0.3:
        p.topic_alias = rng.randint(1, 0xFFFF)
    for _ in range(rng.randint(0, 3)):
        p.user_properties.append((_rand_str(rng), _rand_str(rng)))
    return p


def _rand_packet(rng) -> tuple[Packet, int]:  # qa: complex
    ver = rng.choice([3, 4, 5])
    v5 = ver == 5
    t = rng.randint(1, 15 if v5 else 14)
    pk = Packet(fixed=FixedHeader(type=t), protocol_version=ver)
    if t == 1:
        pk.protocol_name = {3: "MQIsdp", 4: "MQTT", 5: "MQTT"}[ver]
        pk.clean_start = rng.random() < 0.5
        pk.keepalive = rng.randint(0, 0xFFFF)
        pk.client_id = _rand_str(rng)
        if v5:
            if rng.random() < 0.5:
                pk.properties.session_expiry = rng.randint(0, 2**32 - 1)
            if rng.random() < 0.3:
                pk.properties.receive_maximum = rng.randint(1, 0xFFFF)
        if rng.random() < 0.4:
            pk.will = Will(topic=_rand_str(rng, 1), qos=rng.randint(0, 2),
                           retain=rng.random() < 0.5,
                           payload=rng.randbytes(rng.randint(0, 32)))
            if v5 and rng.random() < 0.5:
                pk.will.properties.will_delay = rng.randint(0, 1000)
        pk.username_flag = rng.random() < 0.5
        if pk.username_flag:
            pk.username = rng.randbytes(rng.randint(0, 12))
            pk.password_flag = rng.random() < 0.5
        elif v5:
            pk.password_flag = rng.random() < 0.3
        if pk.password_flag:
            pk.password = rng.randbytes(rng.randint(0, 12))
    elif t == 2:
        pk.session_present = rng.random() < 0.5
        pk.reason_code = rng.randint(0, 255)
        if v5 and rng.random() < 0.5:
            pk.properties.assigned_client_id = _rand_str(rng, 1)
            pk.properties.maximum_qos = rng.randint(0, 1)
    elif t == 3:
        pk.fixed.qos = rng.randint(0, 2)
        pk.fixed.dup = pk.fixed.qos > 0 and rng.random() < 0.3
        pk.fixed.retain = rng.random() < 0.3
        pk.topic = _rand_str(rng, 1)
        if pk.fixed.qos:
            pk.packet_id = rng.randint(1, 0xFFFF)
        pk.properties = _rand_props(rng, v5)
        pk.payload = rng.randbytes(rng.randint(0, 64))
    elif t in (4, 5, 6, 7):
        pk.packet_id = rng.randint(1, 0xFFFF)
        if v5 and rng.random() < 0.6:
            pk.reason_code = rng.choice([0, 16, 128, 131])
            if rng.random() < 0.5:
                pk.properties.reason_string = _rand_str(rng, 1)
    elif t in (8, 10):
        pk.packet_id = rng.randint(1, 0xFFFF)
        for _ in range(rng.randint(1, 4)):
            s = Subscription(filter=_rand_str(rng, 1))
            if t == 8:
                s.qos = rng.randint(0, 2)
                if v5:
                    s.no_local = rng.random() < 0.3
                    s.retain_as_published = rng.random() < 0.3
                    s.retain_handling = rng.randint(0, 2)
            pk.filters.append(s)
        if t == 8 and v5 and rng.random() < 0.4:
            pk.properties.subscription_ids = [rng.randint(1, 1000)]
            for s in pk.filters:
                s.identifier = pk.properties.subscription_ids[0]
    elif t in (9, 11):
        pk.packet_id = rng.randint(1, 0xFFFF)
        if t == 9 or v5:
            pk.reason_codes = [rng.choice([0, 1, 2, 128])
                               for _ in range(rng.randint(1, 4))]
        if v5 and rng.random() < 0.4:
            pk.properties.reason_string = _rand_str(rng, 1)
    elif t in (14, 15):
        if v5:
            pk.reason_code = rng.choice([0, 4, 24, 129, 148])
            if rng.random() < 0.4:
                pk.properties.reason_string = _rand_str(rng, 1)
    return pk, ver


def test_differential_random_roundtrip():
    rng = random.Random(20260731)
    n_checked = 0
    for i in range(3000):
        pk, ver = _rand_packet(rng)
        try:
            raw = pk.encode()
        except (MalformedPacketError, ProtocolError):
            continue  # generator built an unencodable combination
        compare(raw, ver, f"random[{i}]")
        # a well-formed production encode must be ACCEPTED by the
        # independent decoder, not merely agreed on
        fb, remaining, body = frame(raw)
        assert ref_decode(fb, remaining, body, ver) is not None, (
            f"refdecode rejected a production encode: {raw.hex()} v{ver}")
        n_checked += 1
    assert n_checked > 2500, f"only {n_checked} random packets exercised"


# --------------------------------------------------------------------------
# Pass 3: mutation fuzz — near-valid bytes, verdict + canonical agreement
# --------------------------------------------------------------------------

# Hand-built adversarial edge vectors: the cases where two independent
# spec readings most plausibly diverge (UTF-8 well-formedness, varint
# minimality, property-block bounds, flag reserved bits, v3/v5 splits).
EDGE_VECTORS = [
    ("30060002c08000", 4),    # overlong-NUL UTF-8 in topic
    ("300700 03eda08000", 4),  # UTF-16 surrogate in topic
    ("3005000100 41", 4),     # literal NUL in topic
    ("20027f00", 4),          # CONNACK reserved ack-flag bits set
    ("4003000110", 5),        # v5 PUBACK with reason code, no props
    ("100e00044d51545404420000 00000000", 4),  # v4 password w/o username
    ("100f00044d5154540542000000 00000000", 5),  # v5 password w/o username
    ("8209000100000161 30", 5),  # SUBSCRIBE retain-handling 3
    ("f000", 4),              # AUTH on a pre-v5 connection
    ("300a00016103230001ffff", 5),    # property length lies short
    ("300b000161062300012300 02ff", 5),  # duplicate Topic Alias
    ("200500000224 02", 5),   # CONNACK Maximum QoS 2
    ("300c00016108a600000161000162", 5),  # non-minimal prop-id varint
    ("820a000102 0b00000161 00", 5),  # Subscription Identifier 0
    ("101000044d5154540502000003210000 0000", 5),  # Receive Maximum 0
]


@pytest.mark.parametrize("hx,ver", EDGE_VECTORS)
def test_differential_edge_vectors(hx, ver):
    compare(bytes.fromhex(hx.replace(" ", "")), ver, f"edge:{hx[:16]}")


def test_differential_mutation_fuzz():
    rng = random.Random(424242)
    seeds = [(bytes.fromhex(c["raw"]), infer_version(c)) for c in CASES]
    for i in range(120):
        pk, ver = _rand_packet(rng)
        try:
            seeds.append((pk.encode(), ver))
        except (MalformedPacketError, ProtocolError):
            pass
    n = 0
    for i in range(6000):
        raw, ver = seeds[rng.randrange(len(seeds))]
        mutated = bytearray(raw)
        op = rng.random()
        if op < 0.5 and mutated:             # flip one byte
            j = rng.randrange(len(mutated))
            mutated[j] ^= 1 << rng.randrange(8)
        elif op < 0.75 and len(mutated) > 1:  # truncate
            mutated = mutated[:rng.randrange(1, len(mutated))]
        else:                                 # append garbage
            mutated += rng.randbytes(rng.randint(1, 4))
        compare(bytes(mutated), ver, f"mutation[{i}]")
        n += 1
    assert n == 6000
