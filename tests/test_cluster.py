"""Cluster federation e2e suite (ADR 013): route propagation with
aggregation/subsumption, transitive 2-hop forwarding with exactly-once
delivery, loop prevention on a cyclic mesh, link-flap recovery under
``cluster.link`` faults, stale-epoch route flush on peer restart,
retained visibility across nodes, and the QoS1 forward ack-rollback
invariant — all against real brokers on real TCP sockets, driven
deterministically (no sleeps standing in for convergence)."""

import asyncio
import time
from contextlib import asynccontextmanager

import pytest

from maxmq_tpu import faults
from maxmq_tpu.broker import Broker, BrokerOptions, Capabilities, TCPListener
from maxmq_tpu.cluster import (BRIDGE_ID_PREFIX, ClusterManager, DedupWindow,
                               PeerSpec, PeerSpecError, decode_delta,
                               decode_snapshot, encode_delta,
                               encode_snapshot, filter_subsumes,
                               minimal_cover, parse_peers)
from maxmq_tpu.cluster.routes import RouteTable, RouteWireError
from maxmq_tpu.hooks import AllowHook
from maxmq_tpu.mqtt_client import MQTTClient


@pytest.fixture(autouse=True)
def clean_faults():
    faults.clear()
    yield
    faults.clear()


async def make_node(**caps) -> Broker:
    caps.setdefault("sys_topic_interval", 0)
    b = Broker(BrokerOptions(capabilities=Capabilities(**caps)))
    b.add_hook(AllowHook())
    listener = b.add_listener(TCPListener("t", "127.0.0.1:0"))
    await b.serve()
    b.test_port = listener._server.sockets[0].getsockname()[1]
    return b


def make_manager(brokers: dict[str, Broker], name: str,
                 peers: list[str], **kw) -> ClusterManager:
    specs = [PeerSpec(p, "127.0.0.1", brokers[p].test_port)
             for p in peers]
    kw.setdefault("keepalive", 0.5)
    kw.setdefault("backoff_initial_s", 0.05)
    kw.setdefault("backoff_max_s", 0.5)
    mgr = ClusterManager(brokers[name], name, specs, **kw)
    brokers[name].attach_cluster(mgr)
    return mgr


@asynccontextmanager
async def cluster(topology: dict[str, list[str]], **kw):
    """Build one broker + manager per topology entry (peer lists must
    be symmetric, as deployments require) and tear everything down."""
    brokers: dict[str, Broker] = {}
    managers: dict[str, ClusterManager] = {}
    for name in topology:
        brokers[name] = await make_node()
    for name, peers in topology.items():
        managers[name] = make_manager(brokers, name, peers, **kw)
        await managers[name].start()
    try:
        yield brokers, managers
    finally:
        for b in brokers.values():
            await b.close()


async def wait_for(predicate, timeout: float = 10.0, what: str = ""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        await asyncio.sleep(0.02)
    raise AssertionError(f"condition not reached in {timeout}s: {what}")


async def connect(broker: Broker, client_id: str, **kw) -> MQTTClient:
    c = MQTTClient(client_id=client_id, **kw)
    await c.connect("127.0.0.1", broker.test_port)
    return c


# ----------------------------------------------------------------------
# Units: subsumption, cover, wire codec, dedup, peer parsing
# ----------------------------------------------------------------------


def test_filter_subsumes():
    yes = [("sport/#", "sport/+/score"), ("sport/#", "sport"),
           ("#", "a/b/c"), ("+/+", "a/b"), ("sport/+", "sport/x"),
           ("a/#", "a/#"), ("+/#", "a/b/c/d")]
    no = [("sport/+/score", "sport/#"), ("sport/+", "sport/x/y"),
          ("a/b", "a/+"), ("a/b", "a/#"), ("a/+", "a/#"),
          ("a/b", "a/b/c"), ("a/b/c", "a/b"), ("+", "a/b")]
    for g, f in yes:
        assert filter_subsumes(g, f), (g, f)
    for g, f in no:
        assert not filter_subsumes(g, f), (g, f)


def test_minimal_cover():
    assert minimal_cover(["sport/#", "sport/+/score", "news/x"]) == \
        {"sport/#", "news/x"}
    assert minimal_cover(["#", "a", "b/+"]) == {"#"}
    assert minimal_cover([]) == set()
    # equal filters collapse, non-overlapping survive
    assert minimal_cover(["a/+", "a/+", "b"]) == {"a/+", "b"}


def test_wire_codec_roundtrip():
    payload = encode_snapshot("n1", 7, 3, {"a/#", "b/+/c"})
    assert decode_snapshot(payload) == ("n1", 7, 3, ["a/#", "b/+/c"])
    payload = encode_delta("n1", 7, 4, {"x"}, {"y", "z"})
    assert decode_delta(payload) == ("n1", 7, 4, ["x"], ["y", "z"])
    for bad in (b"junk", b"", b"\x78\x9c"):
        with pytest.raises(RouteWireError):
            decode_snapshot(bad)
    with pytest.raises(RouteWireError):
        decode_delta(b'{"v": 99}')


def test_route_table_epoch_seq_rules():
    rt = RouteTable("me", epoch=1)
    assert rt.apply_snapshot("p", 5, 1, ["a/#"])
    assert rt.nodes_for("a/x") == frozenset({"p"})
    # delta chain applies in order, gaps desync
    assert rt.apply_delta("p", 5, 2, ["b"], [])
    assert not rt.apply_delta("p", 5, 4, ["c"], [])     # gap
    assert not rt.apply_delta("p", 6, 3, ["c"], [])     # epoch mismatch
    # stale snapshot (older epoch or older seq) is ignored
    assert not rt.apply_snapshot("p", 4, 99, ["zzz"])
    assert not rt.apply_snapshot("p", 5, 1, ["zzz"])
    assert rt.nodes_for("b") == frozenset({"p"})
    # a fresh epoch replaces everything the old incarnation advertised
    assert rt.apply_snapshot("p", 6, 1, ["c/#"])
    assert rt.nodes_for("b") == frozenset()
    assert rt.nodes_for("c/d") == frozenset({"p"})
    assert rt.flush_node("p") == 1
    assert rt.nodes_for("c/d") == frozenset()


def test_advertisement_split_horizon_and_aggregation():
    rt = RouteTable("me", epoch=1)
    rt.note_local_subscribe("sport/+/score")
    rt.note_local_subscribe("sport/#")
    rt.apply_snapshot("p1", 1, 1, ["news/#"])
    rt.apply_snapshot("p2", 1, 1, ["sport/tennis"])
    # to p1: local cover (sport/# subsumes both locals AND p2's
    # sport/tennis) + p2's routes; p1's own routes never echo back
    assert rt.advertisement_for("p1") == {"sport/#"}
    assert rt.advertisement_for("p2") == {"sport/#", "news/#"}
    # refcounts: two subscribers on one filter, one unsubscribe keeps it
    rt.note_local_subscribe("sport/#")
    assert not rt.note_local_unsubscribe("sport/#")
    assert rt.note_local_unsubscribe("sport/#")
    assert rt.advertisement_for("p1") == {"sport/+/score",
                                          "sport/tennis"}


def test_dedup_window():
    w = DedupWindow(cap=4)
    assert all(w.admit(i) for i in range(4))
    assert not w.admit(2)           # duplicate inside the window
    assert w.admit(5) and w.admit(6)
    assert w.admit(0)               # evicted: admitted again (bounded)


def test_parse_peers():
    peers = parse_peers("b@10.0.0.2:1883, c@host:1885")
    assert peers == [PeerSpec("b", "10.0.0.2", 1883),
                     PeerSpec("c", "host", 1885)]
    assert parse_peers("") == []
    for bad in ("b@nohost", "b@host:xx", "noat:1883", "b b@h:1",
                "b@h:1,b@h:2"):
        with pytest.raises(PeerSpecError):
            parse_peers(bad)


def test_manager_rejects_bad_identity():
    broker = Broker(BrokerOptions())
    with pytest.raises(ValueError):
        ClusterManager(broker, "has/slash", [])
    with pytest.raises(ValueError):
        ClusterManager(broker, "a", [PeerSpec("a", "h", 1)])


# ----------------------------------------------------------------------
# e2e: propagation, forwarding, loops, faults
# ----------------------------------------------------------------------

LINE = {"A": ["B"], "B": ["A", "C"], "C": ["B"]}
MESH = {"A": ["B", "C"], "B": ["A", "C"], "C": ["A", "B"]}


async def test_route_propagation_and_aggregation():
    async with cluster({"A": ["B"], "B": ["A"]}) as (brokers, mgrs):
        sub = await connect(brokers["B"], "sub")
        await sub.subscribe("sport/+/score", "sport/#", "news/x")
        await wait_for(lambda: mgrs["A"].routes.nodes.get("B") and
                       mgrs["A"].routes.nodes["B"].filters ==
                       {"sport/#", "news/x"},
                       what="aggregated routes at A")
        # subsumption: sport/+/score never crossed the wire
        assert mgrs["A"].routes.nodes_for("sport/t/score") == \
            frozenset({"B"})
        # dropping the broad filter re-advertises the narrow one
        await sub.unsubscribe("sport/#")
        await wait_for(lambda: mgrs["A"].routes.nodes["B"].filters ==
                       {"sport/+/score", "news/x"},
                       what="re-advertisement after unsubscribe")
        await sub.disconnect()


async def test_two_hop_exactly_once_with_qos():
    """Line A-B-C: a QoS1 publish at A reaches the subscriber at C
    (two hops, transitive routes) exactly once at the link-capped
    QoS."""
    async with cluster(LINE, link_qos=1) as (brokers, mgrs):
        sub = await connect(brokers["C"], "sub")
        await sub.subscribe("sport/#", qos=1)
        await wait_for(lambda: mgrs["A"].routes.nodes_for("sport/x"),
                       what="2-hop route visible at A")
        pub = await connect(brokers["A"], "pub")
        await pub.publish("sport/tennis", b"m1", qos=1)
        msg = await sub.next_message(timeout=5)
        assert (msg.topic, msg.payload, msg.qos) == \
            ("sport/tennis", b"m1", 1)
        # exactly once: no duplicate within a grace window
        with pytest.raises(asyncio.TimeoutError):
            await sub.next_message(timeout=0.4)
        assert mgrs["B"].forwards_delivered == 1    # the relay hop
        assert mgrs["C"].forwards_delivered == 1
        await pub.disconnect()
        await sub.disconnect()


async def test_loop_prevention_on_cycle():
    """Full 3-node mesh: redundant paths (direct + relayed) must
    collapse to exactly one delivery per subscriber via the
    origin/dedup guards; nothing circulates forever."""
    async with cluster(MESH) as (brokers, mgrs):
        sub_b = await connect(brokers["B"], "sub-b")
        sub_c = await connect(brokers["C"], "sub-c")
        await sub_b.subscribe("t/#")
        await sub_c.subscribe("t/#")
        await wait_for(
            lambda: mgrs["A"].routes.nodes_for("t/x") >= {"B", "C"},
            what="cycle routes at A")
        pub = await connect(brokers["A"], "pub")
        for i in range(3):
            await pub.publish("t/x", b"m%d" % i)
        for sub in (sub_b, sub_c):
            got = [await sub.next_message(timeout=5) for _ in range(3)]
            assert [m.payload for m in got] == [b"m0", b"m1", b"m2"]
            with pytest.raises(asyncio.TimeoutError):
                await sub.next_message(timeout=0.4)
        # the redundant relayed copies were dropped by the guards
        await wait_for(lambda: sum(m.loops_dropped
                                   for m in mgrs.values()) >= 3,
                       what="dedup drops observed")
        await pub.disconnect()
        await sub_b.disconnect()
        await sub_c.disconnect()


async def test_link_flap_recovery_local_only_degradation():
    """Killing the A-B link (cluster.link fault) degrades A's
    publishes to local-only; reconnect restores forwarding with no
    duplicates or loops."""
    async with cluster({"A": ["B"], "B": ["A"]}) as (brokers, mgrs):
        sub = await connect(brokers["B"], "sub")
        await sub.subscribe("t/#")
        await wait_for(lambda: mgrs["A"].routes.nodes_for("t/x"),
                       what="routes at A")
        pub = await connect(brokers["A"], "pub")
        await pub.publish("t/x", b"before")
        assert (await sub.next_message(timeout=5)).payload == b"before"

        # kill A's link to B: the pump's next activity (keepalive ping
        # at the latest) trips the armed fault
        link = mgrs["A"].links["B"]
        faults.arm(f"{faults.CLUSTER_LINK}#B", "raise", count=1)
        await wait_for(lambda: not link.connected, what="link down")
        skipped = mgrs["A"].forwards_skipped_down
        await pub.publish("t/x", b"during")
        await wait_for(
            lambda: mgrs["A"].forwards_skipped_down > skipped,
            what="forward skipped while down")
        with pytest.raises(asyncio.TimeoutError):
            await sub.next_message(timeout=0.4)   # local-only at B

        await wait_for(lambda: link.connected, what="link recovered")
        assert mgrs["A"].link_flaps >= 1
        await pub.publish("t/x", b"after")
        assert (await sub.next_message(timeout=5)).payload == b"after"
        with pytest.raises(asyncio.TimeoutError):
            await sub.next_message(timeout=0.4)   # and exactly once
        await pub.disconnect()
        await sub.disconnect()


async def test_stale_epoch_flush_on_peer_restart():
    """A restarted peer announces a fresh epoch; its old advertised
    routes are flushed even though the delta chain broke."""
    brokers = {"A": await make_node(), "B": await make_node()}
    port_b = brokers["B"].test_port
    mgr_a = make_manager(brokers, "A", ["B"])
    mgr_b = make_manager(brokers, "B", ["A"], epoch=1000)
    await mgr_a.start()
    await mgr_b.start()
    try:
        sub = await connect(brokers["B"], "sub")
        await sub.subscribe("old/#")
        await wait_for(lambda: mgr_a.routes.nodes_for("old/x"),
                       what="routes from first incarnation")
        # B restarts: same address, fresh epoch, no subscribers
        await brokers["B"].close()
        b2 = Broker(BrokerOptions(
            capabilities=Capabilities(sys_topic_interval=0)))
        b2.add_hook(AllowHook())
        b2.add_listener(TCPListener("t", f"127.0.0.1:{port_b}"))
        brokers["B"] = b2
        mgr_b2 = make_manager(brokers, "B", ["A"], epoch=2000)
        await b2.serve()
        b2.test_port = port_b
        await wait_for(lambda: mgr_b2.links["A"].connected,
                       what="restarted B redialed A")
        await wait_for(
            lambda: mgr_a.routes.nodes.get("B") is not None
            and mgr_a.routes.nodes["B"].epoch == 2000
            and not mgr_a.routes.nodes["B"].filters,
            what="stale routes flushed by the fresh epoch")
        assert mgr_a.routes.nodes_for("old/x") == frozenset()
    finally:
        for b in brokers.values():
            await b.close()


async def test_retained_message_visible_across_nodes():
    """Retained state floods the mesh: a subscriber that appears at a
    DIFFERENT node after the publish still gets the retained copy."""
    async with cluster({"A": ["B"], "B": ["A"]}) as (brokers, mgrs):
        await wait_for(lambda: mgrs["A"].links["B"].connected,
                       what="link up")
        pub = await connect(brokers["A"], "pub")
        await pub.publish("state/door", b"open", retain=True)
        await wait_for(
            lambda: brokers["B"].topics.retained_get("state/door")
            is not None, what="retained replicated to B")
        sub = await connect(brokers["B"], "late-sub")
        await sub.subscribe("state/#")
        msg = await sub.next_message(timeout=5)
        assert (msg.topic, msg.payload, msg.retain) == \
            ("state/door", b"open", True)
        # retained clear propagates too
        await pub.publish("state/door", b"", retain=True)
        await wait_for(
            lambda: brokers["B"].topics.retained_get("state/door")
            is None, what="retained clear replicated")
        await pub.disconnect()
        await sub.disconnect()


async def test_qos1_forward_ack_rollback_on_refused_send():
    """A QoS1 forward the link queue refuses must withdraw its
    provisional ack entry (the ADR-012 no-leak invariant on the
    bridge) — and an accepted one completes the PUBACK round trip."""
    async with cluster({"A": ["B"], "B": ["A"]},
                       link_qos=1) as (brokers, mgrs):
        link = mgrs["A"].links["B"]
        await wait_for(lambda: link.connected, what="link up")
        acks_before = dict(link.client._acks)
        # accepted forward: acked by the peer broker
        assert link.forward("$cluster/fwd/A/900/1/1/q/t", b"ok", qos=1)
        await wait_for(lambda: link.forwards_acked == 1,
                       what="PUBACK round trip")
        # refused forward: wedge the queue entry cap
        link.outbound._maxsize = 1
        link.outbound.put_nowait(b"\x00", 1)       # fills the queue
        assert not link.forward("$cluster/fwd/A/901/1/1/q/t",
                                b"no", qos=1)
        assert link.forwards_refused == 1
        assert link.client._acks == acks_before    # nothing leaked
        # byte-budget refusal path counts without touching acks either
        link.outbound._maxsize = 8192
        link.byte_budget = 8
        assert not link.forward("$cluster/fwd/A/902/1/1/q/t",
                                b"x" * 64, qos=1)
        assert link.forwards_refused == 2
        assert link.client._acks == acks_before


async def test_route_apply_fault_desyncs_then_resyncs():
    """An injected cluster.route_apply failure on a delta flushes the
    peer's routes and the sync-request round trip restores them."""
    async with cluster({"A": ["B"], "B": ["A"]}) as (brokers, mgrs):
        sub = await connect(brokers["B"], "sub")
        await sub.subscribe("one/#")
        await wait_for(lambda: mgrs["A"].routes.nodes_for("one/x"),
                       what="initial route")
        faults.arm(faults.CLUSTER_ROUTE_APPLY, "raise", count=1)
        await sub.subscribe("two/#")       # delta A fails to apply
        await wait_for(lambda: mgrs["A"].route_apply_failures == 1,
                       what="apply fault fired")
        await wait_for(
            lambda: mgrs["A"].routes.nodes_for("one/x")
            and mgrs["A"].routes.nodes_for("two/x"),
            what="resynced after desync")
        assert mgrs["A"].route_desyncs >= 1
        await sub.disconnect()


async def test_forward_dedup_is_epoch_scoped_and_topics_validated():
    """A restarted origin restarts its message ids under a fresh
    epoch: the dedup window must admit them (not swallow them as
    replays), while stale-incarnation replays and $-topic/wildcard
    smuggling stay rejected."""
    from maxmq_tpu.protocol.codec import FixedHeader, PacketType as PT
    from maxmq_tpu.protocol.packets import Packet
    async with cluster({"A": ["B"], "B": ["A"]}) as (brokers, mgrs):
        a = mgrs["A"]

        async def fwd(topic: str) -> bool:
            p = Packet(fixed=FixedHeader(type=PT.PUBLISH),
                       topic=topic, payload=b"x")
            before = a.forwards_delivered
            await a._handle_fwd(None, "B", topic.split("/"), p)
            return a.forwards_delivered > before

        assert await fwd("$cluster/fwd/B/1/1/1/0/t/x")
        assert not await fwd("$cluster/fwd/B/1/1/1/0/t/x")  # duplicate
        # fresh epoch, same msgid: a restarted B must get through
        assert await fwd("$cluster/fwd/B/2/1/1/0/t/x")
        # stale incarnation replay stays dropped
        assert not await fwd("$cluster/fwd/B/1/2/1/0/t/x")
        assert a.loops_dropped == 2
        # inner-topic validation: $-state and wildcards never enter
        rejected = a.inbound_rejected
        assert not await fwd("$cluster/fwd/B/2/7/1/0r/$SYS/broker/x")
        assert not await fwd("$cluster/fwd/B/2/8/1/0/a/#")
        assert a.inbound_rejected == rejected + 2


async def test_reserved_namespace_rejects_non_bridge_clients():
    """$cluster/* from an ordinary client is dropped, and a client
    merely wearing the bridge id prefix for an unknown peer is too."""
    async with cluster({"A": ["B"], "B": ["A"]}) as (brokers, mgrs):
        sub = await connect(brokers["A"], "sub")
        await sub.subscribe("t/#")
        evil = await connect(brokers["A"], "evil")
        await evil.publish("$cluster/fwd/Z/1/1/0/t/x", b"spoof")
        evil2 = await connect(brokers["A"], BRIDGE_ID_PREFIX + "Z")
        await evil2.publish("$cluster/fwd/Z/2/1/0/t/x", b"spoof2")
        with pytest.raises(asyncio.TimeoutError):
            await sub.next_message(timeout=0.5)
        assert mgrs["A"].forwards_delivered == 0
        for c in (sub, evil, evil2):
            await c.disconnect()


async def test_cluster_metrics_and_sys_exposed():
    from maxmq_tpu.metrics import Registry, register_broker_metrics
    async with cluster({"A": ["B"], "B": ["A"]}) as (brokers, mgrs):
        sub = await connect(brokers["B"], "sub")
        await sub.subscribe("m/#")
        await wait_for(lambda: mgrs["A"].routes.nodes_for("m/x"),
                       what="routes at A")
        pub = await connect(brokers["A"], "pub")
        await pub.publish("m/x", b"hi")
        assert (await sub.next_message(timeout=5)).payload == b"hi"
        registry = Registry()
        register_broker_metrics(registry, brokers["A"])
        page = registry.expose()
        assert "maxmq_cluster_routes_held 1" in page
        assert "maxmq_cluster_links_up 1" in page
        assert "maxmq_cluster_forwards_sent_total 1" in page
        assert 'maxmq_cluster_link_state{peer="B"} 1' in page
        sys = brokers["A"]._sys_cluster_entries()
        assert sys["$SYS/broker/cluster/node_id"] == "A"
        assert sys["$SYS/broker/cluster/forwards_sent"] == 1
        await pub.disconnect()
        await sub.disconnect()


async def test_bootstrap_builds_cluster_from_config():
    from maxmq_tpu.bootstrap import build_broker
    from maxmq_tpu.utils.config import Config
    from maxmq_tpu.utils.logger import new_logger
    conf = Config(cluster_node_id="n1",
                  cluster_peers="n2@127.0.0.1:19999",
                  cluster_link_qos=1, cluster_max_hops=2,
                  mqtt_tcp_address="127.0.0.1:0",
                  metrics_enabled=False, matcher="")
    broker = build_broker(conf, new_logger(level="error"))
    mgr = broker.cluster
    assert mgr is not None and mgr.node_id == "n1"
    assert mgr.link_qos == 1 and mgr.max_hops == 2
    assert set(mgr.links) == {"n2"}
    # no cluster_node_id: no manager attached
    conf2 = Config(mqtt_tcp_address="127.0.0.1:0",
                   metrics_enabled=False, matcher="")
    assert build_broker(conf2, new_logger(level="error")).cluster is None


async def test_client_surfaces_connack_and_transport_errors():
    """mqtt_client hardening (ADR 013 satellite): CONNACK reason and
    session-present are caller-visible, and a torn transport is
    recorded instead of swallowed."""
    broker = await make_node()
    try:
        c = MQTTClient(client_id="persist", clean_start=False)
        await c.connect("127.0.0.1", broker.test_port)
        assert c.connack_reason == 0 and c.session_present is False
        await c.subscribe("a/b", qos=1)
        await c.disconnect()
        c2 = MQTTClient(client_id="persist", clean_start=False)
        await c2.connect("127.0.0.1", broker.test_port)
        assert c2.session_present is True
        # server-side stop tears the transport mid-session: the read
        # loop records the cause instead of dying silently
        server_client = broker.clients.get("persist")
        server_client.writer.transport.abort()
        await c2.wait_closed(timeout=5)
        assert c2.transport_error is not None or c2._closed.is_set()
        await c2.close()
    finally:
        await broker.close()
