"""ADR-023 MQTT+ content plane: predicate-subscription parsing and
rejection at SUBSCRIBE, the vectorized evaluator against its scalar
reference oracle (randomized differential), delivery masking and
windowed aggregation through a live broker, fail-open under injected
faults, registry cleanup, the pluggable-event-loop bootstrap knob, and
the predicate-annotated cluster route stretch."""

import asyncio
import json
import random
from contextlib import asynccontextmanager

import numpy as np
import pytest

from maxmq_tpu import faults
from maxmq_tpu.broker import Broker, BrokerOptions, Capabilities, TCPListener
from maxmq_tpu.filtering.columnar import (ColumnarEvaluator, build_columns,
                                          eval_batch_numpy,
                                          eval_reference_batch)
from maxmq_tpu.filtering.expr import ExprError, compile_expr, decode_payload
from maxmq_tpu.filtering.plane import parse_spec
from maxmq_tpu.filtering.window import WindowAgg
from maxmq_tpu.hooks import AllowHook
from maxmq_tpu.mqtt_client import MQTTClient
from maxmq_tpu.protocol.codec import FixedHeader, PacketType as PT
from maxmq_tpu.protocol.packets import Packet, Subscription


@pytest.fixture(autouse=True)
def clean_faults():
    faults.clear()
    yield
    faults.clear()


@asynccontextmanager
async def running_broker(**caps):
    caps.setdefault("sys_topic_interval", 0)
    b = Broker(BrokerOptions(capabilities=Capabilities(**caps)))
    b.add_hook(AllowHook())
    listener = b.add_listener(TCPListener("t1", "127.0.0.1:0"))
    await b.serve()
    b.test_port = listener._server.sockets[0].getsockname()[1]
    try:
        yield b
    finally:
        await b.close()


async def connect(broker, client_id="", version=4, **kw) -> MQTTClient:
    c = MQTTClient(client_id=client_id, version=version, **kw)
    await c.connect("127.0.0.1", broker.test_port)
    return c


# ----------------------------------------------------------------------
# Expression compiler + vectorized evaluator (no broker)
# ----------------------------------------------------------------------


FIELDS = ("payload.a", "payload.b", "payload.c.d")


def _gen_expr(rng, depth=0) -> str:
    if depth >= 3 or rng.random() < 0.4:
        f = rng.choice(FIELDS)
        op = rng.choice((">", ">=", "<", "<=", "==", "!="))
        return f"{f}{op}{round(rng.uniform(-5, 5), 2)}"
    r = rng.random()
    a, b = _gen_expr(rng, depth + 1), _gen_expr(rng, depth + 1)
    if r < 0.4:
        return f"({a})&&({b})"
    if r < 0.8:
        return f"({a})||({b})"
    return f"!({a})"


def _gen_payload(rng):
    r = rng.random()
    if r < 0.08:
        return None                         # undecodable publish
    obj = {}
    if rng.random() < 0.85:
        obj["a"] = round(rng.uniform(-6, 6), 3)
    if rng.random() < 0.7:
        obj["b"] = rng.choice(
            [rng.randint(-5, 5), True, False, "a-string"])
    if rng.random() < 0.6:
        obj["c"] = {"d": round(rng.uniform(-6, 6), 3)}
    return obj


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_differential_vectorized_vs_reference(seed):
    """The vectorized columnar path must agree bit-for-bit with the
    scalar per-message oracle over randomized expressions and payloads
    (missing fields, non-numerics, undecodable messages included)."""
    rng = random.Random(seed)
    preds = [compile_expr(_gen_expr(rng)) for _ in range(50)]
    objs = [_gen_payload(rng) for _ in range(300)]
    union: list[str] = []
    for p in preds:
        for f in p.fields:
            if f not in union:
                union.append(f)
    cols = build_columns(objs, tuple(union))
    ref = eval_reference_batch(preds, objs)
    got = eval_batch_numpy([p.program for p in preds], cols, len(objs))
    assert (got == ref).all()


def test_differential_jnp_backend_parity():
    """The device (jax.numpy) path produces the same masks as NumPy,
    and the evaluator reports which backend actually served."""
    rng = random.Random(99)
    preds = [compile_expr(_gen_expr(rng)) for _ in range(12)]
    objs = [_gen_payload(rng) for _ in range(64)]
    union = tuple({f: None for p in preds for f in p.fields})
    cols = build_columns(objs, union)
    programs = [p.program for p in preds]
    ref = eval_batch_numpy(programs, cols, len(objs))
    ev = ColumnarEvaluator(backend="jnp")
    got = ev.eval_batch(programs, cols, len(objs))
    assert (got == ref).all()


def test_compile_rejects_malformed():
    for bad in ("payload.>3", "temp>30", "payload.a>>3", "payload.a>",
                "(payload.a>1", "payload.a>1)", "payload.a > nan",
                "payload.a>1&&", "$agg", ""):
        with pytest.raises(ExprError):
            compile_expr(bad)


def test_parse_spec_grammar():
    s = parse_spec("$expr=payload.t>30")
    assert s.pred is not None and s.agg is None
    s = parse_spec("$agg=avg&$win=5s&$field=payload.t")
    assert s.agg == "avg" and s.win_s == 5.0 and s.field == "payload.t"
    for bad in ("$agg=median&$win=5s", "$win=5s", "$field=payload.t",
                "$expr=payload.t>1&$expr=payload.t>2", "$agg=avg",
                "$bogus=1", "$agg=avg&$win=0s&$field=payload.t"):
        with pytest.raises(ExprError):
            parse_spec(bad, win_min_s=0.5, win_max_s=3600.0)


@pytest.mark.parametrize("op", ["avg", "sum", "min", "max", "count"])
def test_window_agg_bitcompare(op):
    """Tumbling-window folds must match a naive recomputation of the
    same samples (fp-tolerant; here exact summation order is shared so
    equality is tight)."""
    rng = random.Random(5)
    w = WindowAgg(op, "payload.x", 5.0)
    base = 1000.0                               # aligned: 1000 % 5 == 0
    samples: list[float] = []
    msgs = 0
    for i in range(8):
        vals = np.asarray([rng.uniform(-10, 10)
                           for _ in range(rng.randint(0, 4))])
        n = len(vals) + rng.randint(0, 2)       # some without the field
        assert w.accumulate(n, vals, base + i * 0.5) is None
        samples.extend(vals.tolist())
        msgs += n
    emission = w.accumulate(0, np.zeros(0), base + 5.0)
    assert emission is not None
    assert emission["window_start"] == base
    assert emission["count"] == msgs
    naive = {"avg": (sum(samples) / len(samples)) if samples else None,
             "sum": sum(samples) if samples else None,
             "min": min(samples) if samples else None,
             "max": max(samples) if samples else None,
             "count": msgs}[op]
    if naive is None:
        assert emission["value"] is None
    else:
        assert abs(emission["value"] - naive) < 1e-9


# ----------------------------------------------------------------------
# Broker integration
# ----------------------------------------------------------------------


async def test_subscribe_rejects_malformed_options():
    async with running_broker() as broker:
        c = await connect(broker, "c1", version=5)
        granted = await c.subscribe("s/t?$expr=payload..bad>3")
        assert granted == [0x8F]
        granted = await c.subscribe("s/t?$agg=median&$win=5s")
        assert granted == [0x8F]
        granted = await c.subscribe("$share/g/s/t?$expr=payload.a>1")
        assert granted == [0x8F]
        assert broker.content.rejected_subscribes == 3
        assert broker.content.active == 0
        # a valid one still lands, under the BASE filter
        granted = await c.subscribe("s/t?$expr=payload.a>1")
        assert granted == [0]
        assert broker.content.get("c1", "s/t") is not None
        await c.disconnect()


async def test_content_quota_suback():
    async with running_broker(filter_max_subscriptions=1) as broker:
        c = await connect(broker, "c1", version=5)
        assert await c.subscribe("a/1?$expr=payload.a>1") == [0]
        assert await c.subscribe("a/2?$expr=payload.a>1") == [0x97]
        assert broker.content.active == 1
        await c.disconnect()


async def test_predicate_masks_delivery_plain_untouched():
    async with running_broker() as broker:
        pred = await connect(broker, "pred")
        await pred.subscribe(("s/t?$expr=payload.temp>30", 0))
        plain = await connect(broker, "plain")
        await plain.subscribe(("s/t", 0))
        pub = await connect(broker, "pub")
        for t in (10, 50, 20, 70):
            await pub.publish("s/t", json.dumps({"temp": t}).encode())
        got_plain, got_pred = [], []
        for _ in range(4):
            m = await plain.next_message(timeout=2)
            got_plain.append(json.loads(m.payload)["temp"])
        for _ in range(2):
            m = await pred.next_message(timeout=2)
            got_pred.append(json.loads(m.payload)["temp"])
        with pytest.raises(asyncio.TimeoutError):
            await pred.next_message(timeout=0.3)
        assert got_plain == [10, 50, 20, 70]    # [MQTT-4.6.0] order
        assert got_pred == [50, 70]
        assert broker.content.masked == 2
        for c in (pred, plain, pub):
            await c.disconnect()


async def test_v5_user_property_carriage():
    """v5 carries content options out-of-band: a ``maxmq-filter`` user
    property ``<filter>?<options>`` on the SUBSCRIBE, leaving the
    filter string itself untouched on the wire."""
    async with running_broker() as broker:
        c = await connect(broker, "c1", version=5)
        pid = c._alloc_id()
        pkt = Packet(fixed=FixedHeader(type=PT.SUBSCRIBE),
                     protocol_version=5, packet_id=pid,
                     filters=[Subscription(filter="s/t", qos=0)])
        pkt.properties.user_properties = [
            ("maxmq-filter", "s/t?$expr=payload.temp>30")]
        fut = c._await_ack(PT.SUBACK, pid)
        c.writer.write(pkt.encode())
        await c.writer.drain()
        ack = await asyncio.wait_for(fut, 5)
        assert ack.reason_codes == [0]
        assert broker.content.get("c1", "s/t") is not None
        pub = await connect(broker, "pub")
        await pub.publish("s/t", json.dumps({"temp": 10}).encode())
        await pub.publish("s/t", json.dumps({"temp": 40}).encode())
        m = await c.next_message(timeout=2)
        assert json.loads(m.payload)["temp"] == 40
        await c.disconnect()
        await pub.disconnect()


async def test_retained_delivery_predicate_gated():
    async with running_broker() as broker:
        pub = await connect(broker, "pub")
        await pub.publish("r/1", json.dumps({"temp": 10}).encode(),
                          retain=True)
        await pub.publish("r/2", json.dumps({"temp": 50}).encode(),
                          retain=True)
        await asyncio.sleep(0.05)
        c = await connect(broker, "c1")
        await c.subscribe(("r/+?$expr=payload.temp>30", 0))
        m = await c.next_message(timeout=2)
        assert m.topic == "r/2"
        with pytest.raises(asyncio.TimeoutError):
            await c.next_message(timeout=0.3)
        await c.disconnect()
        await pub.disconnect()


async def test_aggregate_window_emission_e2e():
    async with running_broker(filter_window_min_s=0.5) as broker:
        agg = await connect(broker, "agg")
        await agg.subscribe(
            ("s/t?$agg=sum&$win=1s&$field=payload.v", 0))
        pub = await connect(broker, "pub")
        vals = [1.5, 2.5, 4.0]
        for v in vals:
            await pub.publish("s/t", json.dumps({"v": v}).encode())
        # raw publishes are never delivered to an aggregate-only sub;
        # the synthesized window publish arrives on the base topic
        # after the 1s window closes on the housekeeping tick
        m = await agg.next_message(timeout=4)
        row = json.loads(m.payload)
        assert row["op"] == "sum" and row["filter"] == "s/t"
        assert abs(row["value"] - sum(vals)) < 1e-9
        assert row["count"] == len(vals)
        assert broker.content.agg_emitted == 1
        await agg.disconnect()
        await pub.disconnect()


async def test_filter_eval_fault_fails_open():
    """An injected filter.eval fault must deliver UNFILTERED (fail
    open) — losing filtering fidelity, never messages."""
    async with running_broker() as broker:
        pred = await connect(broker, "pred")
        await pred.subscribe(("s/t?$expr=payload.temp>30", 0))
        pub = await connect(broker, "pub")
        faults.arm(faults.FILTER_EVAL, "raise", count=-1)
        await pub.publish("s/t", json.dumps({"temp": 10}).encode())
        m = await pred.next_message(timeout=2)   # non-passing, delivered
        assert json.loads(m.payload)["temp"] == 10
        assert broker.content.eval_errors >= 1
        await pred.disconnect()
        await pub.disconnect()


async def test_unsubscribe_and_purge_cleanup():
    async with running_broker() as broker:
        c = await connect(broker, "c1")
        await c.subscribe(("s/t?$expr=payload.a>1", 0))
        assert broker.content.active == 1
        # UNSUBSCRIBE accepts the suffixed spelling and the base one
        await c.unsubscribe("s/t?$expr=payload.a>1")
        assert broker.content.active == 0
        assert broker.content.get("c1", "s/t") is None
        await c.subscribe(("s/t?$expr=payload.a>1", 0))
        # a plain re-SUBSCRIBE on the same filter replaces the options
        await c.subscribe(("s/t", 0))
        assert broker.content.active == 0
        await c.subscribe(("s/t?$expr=payload.a>1", 0))
        await c.disconnect()
        await asyncio.sleep(0.05)   # clean session purge drops content
        assert broker.content.active == 0


async def test_disabled_plane_plain_path_untouched():
    """content_filtering=False: no plane is constructed, ``?`` stays an
    ordinary topic character, and QoS0 fan-out still rides the ADR-019
    template fast path."""
    async with running_broker(content_filtering=False) as broker:
        assert broker.content is None
        c = await connect(broker, "c1")
        # the suffix spelling is now a LITERAL filter (and '?' is not
        # a wildcard): it matches only its own literal topic
        await c.subscribe(("s/t?$expr=payload.a>1", 0))
        await c.subscribe(("s/t", 0))
        pub = await connect(broker, "pub")
        sends0 = broker.overload.template_sends
        await pub.publish("s/t", json.dumps({"a": 0}).encode())
        m = await c.next_message(timeout=2)
        assert m.topic == "s/t"
        assert broker.overload.template_sends > sends0
        await c.disconnect()
        await pub.disconnect()


# ----------------------------------------------------------------------
# Satellites: pluggable event loop + predicate-annotated routes
# ----------------------------------------------------------------------


def test_install_event_loop_policies():
    from maxmq_tpu.bootstrap import install_event_loop
    orig = asyncio.get_event_loop_policy()
    try:
        with pytest.raises(ValueError):
            install_event_loop("twisted")
        try:
            import uvloop                        # noqa: F401
            have_uvloop = True
        except ImportError:
            have_uvloop = False
        assert install_event_loop("asyncio") == "asyncio"
        # 'uvloop' falls back cleanly when the module is absent;
        # 'auto' never fails either way
        assert install_event_loop("uvloop") == (
            "uvloop" if have_uvloop else "asyncio")
        assert install_event_loop("auto") in ("uvloop", "asyncio")
    finally:
        asyncio.set_event_loop_policy(orig)


def test_route_table_pred_annotations():
    from maxmq_tpu.cluster.routes import (RouteTable, decode_snapshot,
                                          decode_snapshot_preds,
                                          encode_snapshot)
    wire = encode_snapshot("n1", 7, 1, {"a/#", "b/c"},
                           preds={"a/#": ["payload.t>30"]})
    # pre-ADR-023 decoders keep reading the same snapshot
    assert decode_snapshot(wire) == ("n1", 7, 1, ["a/#", "b/c"])
    assert decode_snapshot_preds(wire)[4] == {"a/#": ("payload.t>30",)}
    rt = RouteTable("me", 1)
    rt.apply_snapshot("n1", 7, 1, ["a/#", "b/c"],
                      preds={"a/#": ("payload.t>30",)})
    assert rt.pred_gate("n1", "a/x") == ("payload.t>30",)
    assert rt.pred_gate("n1", "b/c") is None     # un-annotated filter
    assert rt.pred_gate("n1", "nope") is None    # peer not a target
    # a delta add conservatively un-gates until the next snapshot
    rt.apply_delta("n1", 7, 2, add=[], remove=["a/#"])
    assert rt.pred_gate("n1", "a/x") is None


def test_manager_content_gate_skips_fully_masked_peer():
    from maxmq_tpu.cluster import ClusterManager
    b = Broker(BrokerOptions(capabilities=Capabilities(
        sys_topic_interval=0)))
    mgr = ClusterManager(b, "n1", [], session_replication=False,
                         telemetry_interval_s=0, content_routes=True)
    mgr.routes.apply_snapshot(
        "peer", 1, 1, ["s/t"], preds={"s/t": ("payload.temp>30",)})

    class _Pkt:
        payload = b'{"temp": 10}'
    assert mgr._content_gate({"peer"}, "s/t", _Pkt()) == set()
    assert mgr.content_route_skips == 1
    _Pkt.payload = b'{"temp": 40}'
    assert mgr._content_gate({"peer"}, "s/t", _Pkt()) == {"peer"}
    _Pkt.payload = b"not json"                   # predicate false, skip
    assert mgr._content_gate({"peer"}, "s/t", _Pkt()) == set()
    # an un-annotated peer always receives (fail open)
    mgr.routes.apply_snapshot("peer2", 1, 1, ["s/t"])
    _Pkt.payload = b'{"temp": 10}'
    assert mgr._content_gate({"peer2"}, "s/t", _Pkt()) == {"peer2"}


async def test_gated_filters_unguarded_by_plain_or_shared_holder():
    async with running_broker() as broker:
        cp = broker.content
        c1 = await connect(broker, "c1")
        await c1.subscribe(("g/t?$expr=payload.a>1", 0))
        assert cp.gated_filters() == {"g/t": ["payload.a>1"]}
        # a plain holder of the same filter un-gates it
        c2 = await connect(broker, "c2")
        await c2.subscribe(("g/t", 0))
        assert cp.gated_filters() == {}
        await c2.unsubscribe("g/t")
        assert cp.gated_filters() == {"g/t": ["payload.a>1"]}
        # so does a $share holder of the same inner filter
        await c2.subscribe(("$share/grp/g/t", 0))
        assert cp.gated_filters() == {}
        await c1.disconnect()
        await c2.disconnect()
